"""Benchmark: training throughput on the reference's headline workload shapes.

Two workloads, mirroring the reference's published benchmark suite
(docs/Experiments.rst:109-150, BASELINE.md), now at REFERENCE scale:

- HIGGS-like: 10.5M rows x 28 dense numerical features, binary objective,
  num_leaves=255, max_bin=255 — the reference's primary speed benchmark
  (10.5M rows, 500 iters, 130.094 s on a 16-core CPU = 40.4 M row*iter/s).
  A 2M-row run of the same shape is reported alongside (the round 1-4
  configuration, kept for cross-round comparability).
- MSLR-like: 2.27M rows x 137 dense features, lambdarank with ~120-doc
  queries, NDCG@10 — the reference's ranking benchmark (2.27M rows,
  70.417 s = 16.1 M row*iter/s).

The metric is throughput in M row*iters/s at the same leaves/bins settings.
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", plus
secondary fields and a phase breakdown of this script's own wall}.
"""
import json
import os
import sys

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 10_500_000))
N2_ROWS = int(os.environ.get("BENCH_ROWS_2M", 2_000_000))
N_ITER = int(os.environ.get("BENCH_ITERS", 60))
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 255))
RANK_ROWS = int(os.environ.get("BENCH_RANK_ROWS", 2_270_000))
RANK_ITER = int(os.environ.get("BENCH_RANK_ITERS", 30))
SKIP_RANK = os.environ.get("BENCH_SKIP_RANK", "") == "1"
SKIP_2M = os.environ.get("BENCH_SKIP_2M", "") == "1"
SKIP_SERVE = os.environ.get("BENCH_SKIP_SERVE", "") == "1"
SKIP_LINEAR = os.environ.get("BENCH_SKIP_LINEAR", "") == "1"
LINEAR_ROWS = int(os.environ.get("BENCH_LINEAR_ROWS", 500_000))
LINEAR_ITER = int(os.environ.get("BENCH_LINEAR_ITERS", 15))
SKIP_GOSS = os.environ.get("BENCH_SKIP_GOSS", "") == "1"
GOSS_ROWS = int(os.environ.get("BENCH_GOSS_ROWS", 2_000_000))
GOSS_ITER = int(os.environ.get("BENCH_GOSS_ITERS", 30))
# non-empty = record host spans (trace_spans=on) and write the flight
# recorder as Chrome trace-event JSON (Perfetto-loadable) to this path
TRACE_PATH = os.environ.get("BENCH_TRACE", "")
# non-empty = append this bench run to the JSONL run ledger at this path
# (kind="bench"; scripts/ledger.py queries/gates it)
LEDGER_PATH = os.environ.get("BENCH_LEDGER", "")

# reference CPU: Higgs 130.094 s / (500 iter * 10.5M rows); MSLR 70.417 s /
# (500 * 2.27M)  [BASELINE.md, docs/Experiments.rst:109-123]
HIGGS_BASELINE = (500 * 10.5e6) / 130.094
MSLR_BASELINE = (500 * 2.27e6) / 70.417


def make_higgs_like(n, f=28, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    logit = X @ w + 0.5 * np.sin(X[:, 0] * 2) * X[:, 1] + 0.3 * rng.randn(n)
    y = (logit > 0).astype(np.float64)
    return X.astype(np.float64), y


def make_mslr_like(n, f=137, docs_per_query=120, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    rel = X @ w + 0.5 * rng.randn(n)
    # 5-grade relevance labels by global quantile, like MSLR-WEB30K
    edges = np.quantile(rel, [0.55, 0.75, 0.9, 0.97])
    y = np.digitize(rel, edges).astype(np.float64)
    sizes = []
    left = n
    while left > 0:
        s = min(left, max(20, int(rng.normal(docs_per_query, 25))))
        sizes.append(s)
        left -= s
    return X.astype(np.float64), y, np.asarray(sizes, dtype=np.int64)


def _phases(timer, wall, traffic=None):
    """Fused-path phase dict for one timed train + its own accounting.

    Device-time attribution (obs_device PR): each finalize bounds device
    execution with a forced 1-element transfer (obs.sync) BEFORE pulling
    the split-log payload, so the old ">90% in logs_transfer" catch-all
    splits into

      device_s   = fused/device_wait   — host blocked on non-overlapped
                   device execution (the pipeline overlaps block i's wait
                   with block i+1's launch, so this is the un-hidden part),
      transfer_s = fused/logs_transfer — the pure device->host log pull,
      host_s     = block trace/compile + async dispatch + per-tree model
                   reconstruction + dataset construction.

    The legacy per-phase keys stay alongside for trend continuity.

    traffic, when given, is the learner's deterministic bytes-per-row
    accounting of the per-split hot loop (SerialTreeLearner.traffic_spec) —
    merged AFTER the wall accounting so accounted_pct stays a pure
    wall-time self-check."""
    t = timer.times
    host_keys = ("fused/block_fn", "fused/dispatch", "fused/host_trees",
                 "dataset construction")
    keys = host_keys + ("fused/device_wait", "fused/logs_transfer")
    out = {k.split("/")[-1]: round(t.get(k, 0.0), 3) for k in keys}
    out["device_s"] = round(t.get("fused/device_wait", 0.0), 3)
    out["transfer_s"] = round(t.get("fused/logs_transfer", 0.0), 3)
    out["host_s"] = round(sum(t.get(k, 0.0) for k in host_keys), 3)
    acc = sum(t.get(k, 0.0) for k in keys)
    out["other"] = round(max(wall - acc, 0.0), 3)
    out["accounted_pct"] = round(100.0 * min(acc / max(wall, 1e-9), 1.0), 1)
    if traffic:
        out["work_layout"] = traffic["work_layout"]
        out["partition_bytes_per_row_split"] = \
            traffic["partition_bytes_per_row"]
        out["hist_gather_bytes_per_row"] = traffic["hist_bytes_per_row"]
        out["split_kernel"] = traffic.get("split_kernel", "off")
        out["launches_per_split"] = traffic.get("launches_per_split", 3)
        out["effective_rows"] = traffic.get("effective_rows", 0)
        out["goss_compact"] = traffic.get("goss_compact", "off")
    return out


def _traffic(bst):
    """traffic_spec of the trained Booster's learner, or None (dense
    builder / unexpected internals — the bench must not fail on it)."""
    try:
        return bst.inner.learner.traffic_spec()
    except Exception:
        return None


def run_higgs(lgb, n_rows, timer):
    from lightgbm_tpu import obs
    with obs.wall("higgs/datagen") as w:
        X, y = make_higgs_like(n_rows)
    t_gen = w.seconds
    params = {
        "objective": "binary",
        "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN,
        "learning_rate": 0.1,
        "verbosity": -1,
        "metric": ["auc"],
        "tpu_iter_block": 20,
    }
    with obs.wall("higgs/construct") as w:
        ds = lgb.Dataset(X, label=y)
        ds.construct()
    t_cons = w.seconds
    # short warmup train populates the persistent compile cache (reference
    # timings likewise exclude one-time setup); every train wall ends in a
    # forced 1-element transfer of the score (PERF.md discipline via obs)
    with obs.wall("higgs/warmup") as w:
        wb = lgb.train(dict(params), ds, num_boost_round=20)
        obs.sync(wb.inner.train_score.score)
    warmup_s = w.seconds
    timer.reset()
    with obs.wall("higgs/train") as w:
        bst = lgb.train(dict(params), ds, num_boost_round=N_ITER)
        obs.sync(bst.inner.train_score.score)
    train_s = w.seconds
    phases = _phases(timer, train_s, _traffic(bst))
    (_, _, auc, _), = bst.eval_train()
    return ((n_rows * N_ITER) / train_s, auc, train_s, warmup_s, t_gen,
            t_cons, phases)


def run_mslr(lgb, timer):
    from lightgbm_tpu import obs
    with obs.wall("mslr/datagen") as w:
        X, y, group = make_mslr_like(RANK_ROWS)
    t_gen = w.seconds
    params = {
        "objective": "lambdarank",
        "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN,
        "learning_rate": 0.1,
        "verbosity": -1,
        "metric": ["ndcg"],
        "eval_at": [10],
        "tpu_iter_block": 10,
    }
    with obs.wall("mslr/construct") as w:
        ds = lgb.Dataset(X, label=y, group=group)
        ds.construct()
    t_cons = w.seconds
    with obs.wall("mslr/warmup") as w:
        wb = lgb.train(dict(params), ds, num_boost_round=10)
        obs.sync(wb.inner.train_score.score)
    warmup_s = w.seconds
    timer.reset()
    with obs.wall("mslr/train") as w:
        bst = lgb.train(dict(params), ds, num_boost_round=RANK_ITER)
        obs.sync(bst.inner.train_score.score)
    train_s = w.seconds
    phases = _phases(timer, train_s, _traffic(bst))
    evals = {name: v for (_, name, v, _) in bst.eval_train()}
    ndcg = evals.get("ndcg@10", next(iter(evals.values())))
    return ((RANK_ROWS * RANK_ITER) / train_s, ndcg, train_s, warmup_s,
            t_gen, t_cons, phases)


def run_linear(lgb):
    """Piecewise-linear leaf trees: full-train wall with the host per-leaf
    solve loop (linear_device=off) vs the batched device fit (on), plus
    prediction parity between the two models. Kernel-level A/B with
    measurement discipline lives in scripts/linear_bisect.py."""
    from lightgbm_tpu import obs
    rng = np.random.RandomState(17)
    X = rng.randn(LINEAR_ROWS, 28)
    w = rng.randn(28) / np.sqrt(28)
    y = X @ w + 0.5 * np.sin(2 * X[:, 0]) + 0.1 * rng.randn(LINEAR_ROWS)
    params = {"objective": "regression", "num_leaves": 63, "max_bin": 255,
              "learning_rate": 0.1, "verbosity": -1, "linear_tree": True,
              "linear_lambda": 0.01}
    out = {}
    boosters = {}
    for dev in ("off", "on"):
        p = dict(params, linear_device=dev)
        ds = lgb.Dataset(X, label=y, params=dict(p))
        ds.construct()
        lgb.train(dict(p), ds, num_boost_round=3)          # warmup/compile
        with obs.wall("linear/train_" + dev) as wl:
            bst = lgb.train(dict(p), ds, num_boost_round=LINEAR_ITER)
            obs.sync(bst.inner.train_score.score)
        out[dev] = wl.seconds
        boosters[dev] = bst
    pred_off = boosters["off"].predict(X[:4096])
    pred_on = boosters["on"].predict(X[:4096])
    return {
        "linear_train_off_s": round(out["off"], 3),
        "linear_train_on_s": round(out["on"], 3),
        "linear_device_speedup": round(out["off"] / max(out["on"], 1e-9), 3),
        "linear_pred_maxdiff": float(np.max(np.abs(pred_off - pred_on))),
        "linear_unit": "train wall s (N=%d F=28 leaves=63 iters=%d)"
                       % (LINEAR_ROWS, LINEAR_ITER),
    }


def run_goss(lgb):
    """GOSS row-compaction A/B: full-train wall with every per-split pass
    over all N padded rows (tpu_goss_compact=off) vs the sorted/sliced
    survivor set of ceil((top_rate+other_rate)*N) rows (on). Kernel-level
    A/B with measurement discipline lives in scripts/goss_bisect.py."""
    from lightgbm_tpu import obs
    X, y = make_higgs_like(GOSS_ROWS, seed=23)
    params = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "max_bin": MAX_BIN, "learning_rate": 0.1, "verbosity": -1,
              "boosting": "goss", "top_rate": 0.2, "other_rate": 0.1,
              "tpu_iter_block": 10}
    out = {}
    eff = {}
    for mode in ("off", "on"):
        p = dict(params, tpu_goss_compact=mode)
        ds = lgb.Dataset(X, label=y)
        ds.construct()
        lgb.train(dict(p), ds, num_boost_round=3)          # warmup/compile
        with obs.wall("goss/train_" + mode) as wl:
            bst = lgb.train(dict(p), ds, num_boost_round=GOSS_ITER)
            obs.sync(bst.inner.train_score.score)
        out[mode] = wl.seconds
        tr = _traffic(bst) or {}
        eff[mode] = tr.get("effective_rows", 0)
    return {
        "goss_off_s": round(out["off"], 3),
        "goss_on_s": round(out["on"], 3),
        "goss_speedup": round(out["off"] / max(out["on"], 1e-9), 3),
        "goss_effective_rows": eff["on"],
        "goss_unit": "train wall s (N=%d F=28 leaves=%d iters=%d "
                     "top=0.2 other=0.1; effective rows off=%d on=%d)"
                     % (GOSS_ROWS, NUM_LEAVES, GOSS_ITER, eff["off"],
                        eff["on"]),
    }


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.timer import global_timer

    if TRACE_PATH:
        from lightgbm_tpu.obs_trace import tracer
        tracer.configure("on")
    h_tp, auc, h_train, h_warm, h_gen, h_cons, h_ph = run_higgs(
        lgb, N_ROWS, global_timer)
    result = {
        "metric": "higgs_like_binary_train_throughput",
        "value": round(h_tp / 1e6, 4),
        "unit": "M rows*iters/s (N=%d F=28 leaves=%d bins=%d iters=%d; "
                "auc=%.4f; train=%.1fs warmup=%.1fs datagen=%.1fs "
                "construct=%.1fs)"
                % (N_ROWS, NUM_LEAVES, MAX_BIN, N_ITER, auc, h_train,
                   h_warm, h_gen, h_cons),
        "vs_baseline": round(h_tp / HIGGS_BASELINE, 4),
        "train_breakdown": h_ph,
    }
    if not SKIP_2M:
        try:
            tp2, auc2, tr2, wm2, _, _, ph2 = run_higgs(lgb, N2_ROWS,
                                                       global_timer)
            result["value_2m"] = round(tp2 / 1e6, 4)
            result["unit_2m"] = (
                "M rows*iters/s (N=%d; auc=%.4f; train=%.1fs warmup=%.1fs)"
                % (N2_ROWS, auc2, tr2, wm2))
            result["vs_baseline_2m"] = round(tp2 / HIGGS_BASELINE, 4)
        except Exception as e:  # pragma: no cover - report, don't fail
            result["error_2m"] = "%s: %s" % (type(e).__name__, str(e)[:200])
    if not SKIP_RANK:
        try:
            (r_tp, ndcg, r_train, r_warm, r_gen, r_cons,
             r_ph) = run_mslr(lgb, global_timer)
            result["rank_value"] = round(r_tp / 1e6, 4)
            result["rank_unit"] = (
                "M rows*iters/s (MSLR-like N=%d F=137 leaves=%d bins=%d "
                "iters=%d; ndcg@10=%.4f; train=%.1fs warmup=%.1fs "
                "datagen=%.1fs construct=%.1fs)"
                % (RANK_ROWS, NUM_LEAVES, MAX_BIN, RANK_ITER, ndcg,
                   r_train, r_warm, r_gen, r_cons))
            result["rank_vs_baseline"] = round(r_tp / MSLR_BASELINE, 4)
            result["rank_train_breakdown"] = r_ph
        except Exception as e:  # pragma: no cover - report, don't fail
            result["rank_error"] = "%s: %s" % (type(e).__name__, str(e)[:200])
    if not SKIP_SERVE:
        try:
            # serving sidecar: session+batcher throughput vs naive
            # Booster.predict loop (full harness: scripts/serve_bench.py)
            from lightgbm_tpu.serve.bench import run_serve_bench
            sb = run_serve_bench(requests=256, trees=60, num_leaves=63,
                                 n_features=28, train_rows=10_000,
                                 closed_loop_requests=64)
            result["serve_value"] = sb["value"]
            result["serve_unit"] = sb["unit"]
            result["serve_vs_naive"] = sb["vs_baseline"]
            # percentiles derived from the log-bucketed latency histogram
            # (the same buckets GET /metrics exports); exact cumulative
            # counts ride along for offline re-aggregation
            result["serve_p50_ms"] = sb["closed_loop_p50_ms"]
            result["serve_p90_ms"] = sb["closed_loop_p90_ms"]
            result["serve_p99_ms"] = sb["closed_loop_p99_ms"]
            result["serve_p999_ms"] = sb["closed_loop_p999_ms"]
            result["serve_hist_buckets"] = sb["closed_loop_hist_buckets"]
        except Exception as e:  # pragma: no cover - report, don't fail
            result["serve_error"] = "%s: %s" % (type(e).__name__,
                                                str(e)[:200])
    if not SKIP_LINEAR:
        try:
            result.update(run_linear(lgb))
        except Exception as e:  # pragma: no cover - report, don't fail
            result["linear_error"] = "%s: %s" % (type(e).__name__,
                                                 str(e)[:200])
    if not SKIP_GOSS:
        try:
            result.update(run_goss(lgb))
        except Exception as e:  # pragma: no cover - report, don't fail
            result["goss_error"] = "%s: %s" % (type(e).__name__,
                                               str(e)[:200])
    # full structured-counter view of the run (dataset cache traffic, fused
    # dispatch/flush, per-tree growth, auto-knob resolutions, bench walls)
    result["telemetry"] = lgb.obs.telemetry.snapshot()
    # retrace detector verdict, hoisted for headline visibility (PERF.md
    # per-train compile budget; per-entry detail under telemetry)
    result["jit_compiles"] = result["telemetry"]["jit_compiles"]["total"]
    if LEDGER_PATH:
        try:
            from lightgbm_tpu import obs_ledger
            from lightgbm_tpu.config import Config
            cfg = Config.from_params({
                "objective": "binary", "num_leaves": NUM_LEAVES,
                "max_bin": MAX_BIN, "learning_rate": 0.1, "verbosity": -1,
                "metric": ["auc"], "tpu_iter_block": 20,
                "obs_ledger": True, "obs_ledger_path": LEDGER_PATH})
            obs_ledger.record_run(
                cfg, "bench", N_ROWS, 28,
                extra={"train_s": round(h_train, 3),
                       "throughput_M": result["value"],
                       "train_breakdown": h_ph})
            result["ledger_path"] = LEDGER_PATH
        except Exception as e:  # pragma: no cover - report, don't fail
            result["ledger_error"] = "%s: %s" % (type(e).__name__,
                                                 str(e)[:200])
    if TRACE_PATH:
        from lightgbm_tpu.obs_trace import tracer
        result["trace_path"] = TRACE_PATH
        result["trace_events"] = tracer.dump(TRACE_PATH)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
