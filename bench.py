"""Benchmark: HIGGS-like binary classification training throughput.

Mirrors the reference's headline benchmark shape (docs/Experiments.rst:109 —
HIGGS 28 dense numerical features, binary objective, 500 iterations) at a
size that fits a single-chip round: the metric is training throughput in
M rows·iterations / second, compared against the reference CPU baseline's
published throughput on the same workload class
(130.094 s for 500 iters × 10.5M rows = 40.4 M row·iter/s, BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 500_000))
N_FEAT = 28
N_ITER = int(os.environ.get("BENCH_ITERS", 100))
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 31))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 63))

# reference CPU Higgs: 130.094 s / (500 iter * 10.5M rows)  [BASELINE.md]
BASELINE_ROWS_ITER_PER_SEC = (500 * 10.5e6) / 130.094


def make_higgs_like(n, f, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    logit = X @ w + 0.5 * np.sin(X[:, 0] * 2) * X[:, 1] + 0.3 * rng.randn(n)
    y = (logit > 0).astype(np.float64)
    return X.astype(np.float64), y


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import lightgbm_tpu as lgb

    X, y = make_higgs_like(N_ROWS, N_FEAT)
    block = int(os.environ.get("BENCH_BLOCK", 10))
    params = {
        "objective": "binary",
        "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN,
        "learning_rate": 0.1,
        "verbosity": -1,
        "metric": ["auc"],
        "tpu_iter_block": block,
    }
    ds = lgb.Dataset(X, label=y)
    # warmup: bins + compiles (first compile is excluded, like the reference's
    # timings which exclude data loading); trains one full fused block so the
    # timed run hits the compile cache
    t0 = time.time()
    warm = lgb.train(dict(params), ds, num_boost_round=block)
    warmup_s = time.time() - t0

    t0 = time.time()
    bst = lgb.train(dict(params), ds, num_boost_round=N_ITER)
    train_s = time.time() - t0

    (_, _, auc, _), = bst.eval_train()
    rows_iter_per_sec = (N_ROWS * N_ITER) / train_s
    result = {
        "metric": "higgs_like_binary_train_throughput",
        "value": round(rows_iter_per_sec / 1e6, 4),
        "unit": "M rows*iters/s (N=%d F=%d leaves=%d bins=%d iters=%d; auc=%.4f; train=%.1fs warmup=%.1fs)"
                % (N_ROWS, N_FEAT, NUM_LEAVES, MAX_BIN, N_ITER, auc, train_s, warmup_s),
        "vs_baseline": round(rows_iter_per_sec / BASELINE_ROWS_ITER_PER_SEC, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
