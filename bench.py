"""Benchmark: training throughput on the reference's headline workload shapes.

Two workloads, mirroring the reference's published benchmark suite
(docs/Experiments.rst:109-150, BASELINE.md):

- HIGGS-like: 28 dense numerical features, binary objective, num_leaves=255,
  max_bin=255 — the reference's primary speed benchmark (10.5M rows, 500
  iters, 130.094 s on a 16-core CPU = 40.4 M row*iter/s).
- MSLR-like: 137 dense features, lambdarank objective with ~120-doc queries,
  NDCG@10 — the reference's ranking benchmark (2.27M rows, 70.417 s =
  16.1 M row*iter/s).

The metric is throughput in M row*iters/s at the same leaves/bins settings;
sizes are scaled to fit a single-chip round (throughput is the comparable
quantity). Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline", plus secondary fields}.
"""
import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 2_000_000))
N_ITER = int(os.environ.get("BENCH_ITERS", 60))
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 255))
RANK_ROWS = int(os.environ.get("BENCH_RANK_ROWS", 500_000))
RANK_ITER = int(os.environ.get("BENCH_RANK_ITERS", 30))
SKIP_RANK = os.environ.get("BENCH_SKIP_RANK", "") == "1"

# reference CPU: Higgs 130.094 s / (500 iter * 10.5M rows); MSLR 70.417 s /
# (500 * 2.27M)  [BASELINE.md, docs/Experiments.rst:109-123]
HIGGS_BASELINE = (500 * 10.5e6) / 130.094
MSLR_BASELINE = (500 * 2.27e6) / 70.417


def make_higgs_like(n, f=28, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    logit = X @ w + 0.5 * np.sin(X[:, 0] * 2) * X[:, 1] + 0.3 * rng.randn(n)
    y = (logit > 0).astype(np.float64)
    return X.astype(np.float64), y


def make_mslr_like(n, f=137, docs_per_query=120, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    rel = X @ w + 0.5 * rng.randn(n)
    # 5-grade relevance labels by global quantile, like MSLR-WEB30K
    edges = np.quantile(rel, [0.55, 0.75, 0.9, 0.97])
    y = np.digitize(rel, edges).astype(np.float64)
    sizes = []
    left = n
    while left > 0:
        s = min(left, max(20, int(rng.normal(docs_per_query, 25))))
        sizes.append(s)
        left -= s
    return X.astype(np.float64), y, np.asarray(sizes, dtype=np.int64)


def run_higgs(lgb):
    X, y = make_higgs_like(N_ROWS)
    params = {
        "objective": "binary",
        "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN,
        "learning_rate": 0.1,
        "verbosity": -1,
        "metric": ["auc"],
        "tpu_iter_block": 20,
    }
    ds = lgb.Dataset(X, label=y)
    # short warmup train populates the persistent compile cache (reference
    # timings likewise exclude one-time setup)
    t0 = time.time()
    lgb.train(dict(params), ds, num_boost_round=20)
    warmup_s = time.time() - t0
    t0 = time.time()
    bst = lgb.train(dict(params), ds, num_boost_round=N_ITER)
    train_s = time.time() - t0
    (_, _, auc, _), = bst.eval_train()
    return (N_ROWS * N_ITER) / train_s, auc, train_s, warmup_s


def run_mslr(lgb):
    X, y, group = make_mslr_like(RANK_ROWS)
    params = {
        "objective": "lambdarank",
        "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN,
        "learning_rate": 0.1,
        "verbosity": -1,
        "metric": ["ndcg"],
        "eval_at": [10],
        "tpu_iter_block": 10,
    }
    ds = lgb.Dataset(X, label=y, group=group)
    t0 = time.time()
    lgb.train(dict(params), ds, num_boost_round=10)
    warmup_s = time.time() - t0
    t0 = time.time()
    bst = lgb.train(dict(params), ds, num_boost_round=RANK_ITER)
    train_s = time.time() - t0
    evals = {name: v for (_, name, v, _) in bst.eval_train()}
    ndcg = evals.get("ndcg@10", next(iter(evals.values())))
    return (RANK_ROWS * RANK_ITER) / train_s, ndcg, train_s, warmup_s


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import lightgbm_tpu as lgb

    h_tp, auc, h_train, h_warm = run_higgs(lgb)
    result = {
        "metric": "higgs_like_binary_train_throughput",
        "value": round(h_tp / 1e6, 4),
        "unit": "M rows*iters/s (N=%d F=28 leaves=%d bins=%d iters=%d; "
                "auc=%.4f; train=%.1fs warmup=%.1fs)"
                % (N_ROWS, NUM_LEAVES, MAX_BIN, N_ITER, auc, h_train, h_warm),
        "vs_baseline": round(h_tp / HIGGS_BASELINE, 4),
    }
    if not SKIP_RANK:
        try:
            r_tp, ndcg, r_train, r_warm = run_mslr(lgb)
            result["rank_value"] = round(r_tp / 1e6, 4)
            result["rank_unit"] = (
                "M rows*iters/s (MSLR-like N=%d F=137 leaves=%d bins=%d "
                "iters=%d; ndcg@10=%.4f; train=%.1fs warmup=%.1fs)"
                % (RANK_ROWS, NUM_LEAVES, MAX_BIN, RANK_ITER, ndcg,
                   r_train, r_warm))
            result["rank_vs_baseline"] = round(r_tp / MSLR_BASELINE, 4)
        except Exception as e:  # pragma: no cover - report, don't fail
            result["rank_error"] = "%s: %s" % (type(e).__name__, str(e)[:200])
    print(json.dumps(result))


if __name__ == "__main__":
    main()
