"""Evaluation metrics.

Equivalent of the reference metric zoo (reference: src/metric/metric.cpp:17
factory; regression_metric.hpp, binary_metric.hpp, rank_metric.hpp,
multiclass_metric.hpp, xentropy_metric.hpp, map_metric.hpp,
dcg_calculator.cpp). Metrics run on host numpy over *converted* predictions
(the objective's ConvertOutput already applied on device) — evaluation is off
the training hot path.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .config import Config
from .utils.log import Log


class Metric:
    name = "metric"
    greater_is_better = False

    def __init__(self, config: Config) -> None:
        self.config = config

    def eval(self, pred: np.ndarray, label: np.ndarray,
             weight: Optional[np.ndarray],
             query_boundaries: Optional[np.ndarray] = None) -> List:
        """Returns [(name, value)] pairs."""
        raise NotImplementedError


def _avg(values: np.ndarray, weight: Optional[np.ndarray]) -> float:
    return float(np.average(values, weights=weight))


class _PointwiseMetric(Metric):
    def point(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def eval(self, pred, label, weight, query_boundaries=None):
        return [(self.name, _avg(self.point(pred.ravel(), label), weight))]


class L2Metric(_PointwiseMetric):
    name = "l2"

    def point(self, p, y):
        return (p - y) ** 2


class RMSEMetric(_PointwiseMetric):
    name = "rmse"

    def eval(self, pred, label, weight, query_boundaries=None):
        return [(self.name, float(np.sqrt(_avg((pred.ravel() - label) ** 2, weight))))]


class L1Metric(_PointwiseMetric):
    name = "l1"

    def point(self, p, y):
        return np.abs(p - y)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def point(self, p, y):
        a = self.config.alpha
        d = y - p
        return np.where(d >= 0, a * d, (a - 1) * d)


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def point(self, p, y):
        a = self.config.alpha
        d = np.abs(p - y)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def point(self, p, y):
        c = self.config.fair_c
        x = np.abs(p - y)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    """Poisson negative log-likelihood (reference: PoissonMetric — eval over
    converted prediction, i.e. the rate)."""
    name = "poisson"

    def point(self, p, y):
        eps = 1e-10
        return p - y * np.log(np.maximum(p, eps))


class MAPEMetric(_PointwiseMetric):
    name = "mape"

    def point(self, p, y):
        return np.abs((y - p) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseMetric):
    """Gamma negative log-likelihood (reference: GammaMetric)."""
    name = "gamma"

    def point(self, p, y):
        psi = 1.0
        theta = -1.0 / np.maximum(p, 1e-10)
        a = psi
        b = -np.log(-theta)
        c = 1.0 / psi * np.log(y / psi) - np.log(y) - 0  # lgamma(1/psi)=0
        return -(y * theta - b) / a - c


class GammaDevianceMetric(_PointwiseMetric):
    """(reference: GammaDevianceMetric)"""
    name = "gamma_deviance"

    def point(self, p, y):
        eps = 1e-10
        frac = y / np.maximum(p, eps)
        return 2.0 * (frac - np.log(np.maximum(frac, eps)) - 1.0)


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def point(self, p, y):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        p = np.maximum(p, eps)
        a = y * np.power(p, 1.0 - rho) / (1.0 - rho)
        b = np.power(p, 2.0 - rho) / (2.0 - rho)
        return -a + b


class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def point(self, p, y):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def point(self, p, y):
        return ((p > 0.5) != (y > 0)).astype(np.float64)


class AUCMetric(Metric):
    """ROC AUC via weighted rank statistic (reference: binary_metric.hpp
    AUCMetric — sorted-by-score positive/negative mass accumulation)."""
    name = "auc"
    greater_is_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        p = pred.ravel()
        y = (label > 0).astype(np.float64)
        w = np.ones_like(y) if weight is None else weight.astype(np.float64)
        order = np.argsort(p, kind="mergesort")
        p, y, w = p[order], y[order], w[order]
        pos_w, neg_w = w * y, w * (1 - y)
        cum_neg = np.cumsum(neg_w)
        # ties: average rank — process by distinct score groups
        _, idx_start = np.unique(p, return_index=True)
        group_id = np.zeros(len(p), dtype=np.int64)
        group_id[idx_start[1:]] = 1
        group_id = np.cumsum(group_id)
        neg_in_group = np.bincount(group_id, weights=neg_w)
        pos_in_group = np.bincount(group_id, weights=pos_w)
        neg_before = np.concatenate([[0.0], np.cumsum(neg_in_group)[:-1]])
        auc_sum = np.sum(pos_in_group * (neg_before + 0.5 * neg_in_group))
        tot_pos, tot_neg = pos_w.sum(), neg_w.sum()
        if tot_pos <= 0 or tot_neg <= 0:
            return [(self.name, 1.0)]
        return [(self.name, float(auc_sum / (tot_pos * tot_neg)))]


class AveragePrecisionMetric(Metric):
    """(reference: AveragePrecisionMetric)"""
    name = "average_precision"
    greater_is_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        p = pred.ravel()
        y = (label > 0).astype(np.float64)
        w = np.ones_like(y) if weight is None else weight.astype(np.float64)
        order = np.argsort(-p, kind="mergesort")
        y, w = y[order], w[order]
        tp = np.cumsum(w * y)
        total = np.cumsum(w)
        precision = tp / np.maximum(total, 1e-20)
        pos_total = (w * y).sum()
        if pos_total <= 0:
            return [(self.name, 1.0)]
        ap = np.sum(precision * w * y) / pos_total
        return [(self.name, float(ap))]


class AucMuMetric(Metric):
    """Multiclass AUC-mu (reference: multiclass_metric.hpp AucMuMetric):
    mean pairwise class AUC on the decision statistic."""
    name = "auc_mu"
    greater_is_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        K = self.config.num_class
        pred = pred.reshape(-1, K)
        lab = label.astype(np.int64)
        w = np.ones(len(lab)) if weight is None else weight
        # auc_mu_weights: KxK misclassification-cost matrix defining each
        # pair's separating direction (reference: multiclass_metric.hpp
        # AucMuMetric::Eval, Kleiman & Page's AUC-mu: the pair (a, b)
        # decision value is t1 * <W[a,:] - W[b,:], scores>)
        if self.config.auc_mu_weights:
            wm = np.asarray(self.config.auc_mu_weights, np.float64)
            if wm.size != K * K:
                from .utils.log import Log
                Log.fatal("auc_mu_weights must have num_class^2 = %d "
                          "entries, got %d", K * K, wm.size)
            wm = wm.reshape(K, K)
        else:
            wm = 1.0 - np.eye(K)
        aucs = []
        auc_helper = AUCMetric(self.config)
        for a in range(K):
            for b in range(a + 1, K):
                m = (lab == a) | (lab == b)
                if not np.any(lab[m] == a) or not np.any(lab[m] == b):
                    continue
                curr_v = wm[a] - wm[b]                      # (K,)
                t1 = curr_v[a] - curr_v[b]
                s = t1 * (pred[m] @ curr_v)
                yy = (lab[m] == a).astype(np.float64)
                aucs.append(auc_helper.eval(s, yy, w[m])[0][1])
        return [(self.name, float(np.mean(aucs)) if aucs else 1.0)]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, pred, label, weight, query_boundaries=None):
        K = self.config.num_class
        pred = pred.reshape(-1, K)
        lab = label.astype(np.int64)
        eps = 1e-15
        p = np.clip(pred[np.arange(len(lab)), lab], eps, 1.0)
        return [(self.name, _avg(-np.log(p), weight))]


class MultiErrorMetric(Metric):
    """Top-k error (reference: MultiErrorMetric with multi_error_top_k)."""
    name = "multi_error"

    def eval(self, pred, label, weight, query_boundaries=None):
        K = self.config.num_class
        k = max(1, self.config.multi_error_top_k)
        pred = pred.reshape(-1, K)
        lab = label.astype(np.int64)
        true_p = pred[np.arange(len(lab)), lab]
        # error when the true class's prob is not within the top k
        rank = np.sum(pred > true_p[:, None], axis=1)
        err = (rank >= k).astype(np.float64)
        return [(self.name, _avg(err, weight))]


class CrossEntropyMetric(_PointwiseMetric):
    name = "cross_entropy"

    def point(self, p, y):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, pred, label, weight, query_boundaries=None):
        # pred is converted: hhat = log1p(exp(score))
        hhat = pred.ravel()
        eps = 1e-15
        p = 1.0 - np.exp(-np.maximum(hhat, eps))
        p = np.clip(p, eps, 1 - eps)
        loss = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        return [(self.name, _avg(loss, weight))]


class KullbackLeiblerMetric(_PointwiseMetric):
    """(reference: KullbackLeiblerDivergence in xentropy_metric.hpp)"""
    name = "kullback_leibler"

    def point(self, p, y):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        yy = np.clip(y, eps, 1 - eps)
        ref = yy * np.log(yy) + (1 - yy) * np.log(1 - yy)
        xe = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return ref + xe


def _dcg_at(gains_sorted: np.ndarray, k: int) -> float:
    k = min(k, len(gains_sorted))
    if k <= 0:
        return 0.0
    disc = 1.0 / np.log2(np.arange(k) + 2.0)
    return float(np.sum(gains_sorted[:k] * disc))


class NDCGMetric(Metric):
    """NDCG at eval_at positions (reference: rank_metric.hpp NDCGMetric +
    dcg_calculator.cpp)."""
    name = "ndcg"
    greater_is_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        if query_boundaries is None:
            Log.fatal("[ndcg]: query data required")
        cfg = self.config
        label_gain = cfg.label_gain or [float(2 ** i - 1) for i in range(31)]
        lg = np.asarray(label_gain)
        ks = cfg.eval_at or [1, 2, 3, 4, 5]
        p = pred.ravel()
        results = {k: [] for k in ks}
        qb = query_boundaries
        for q in range(len(qb) - 1):
            s, e = qb[q], qb[q + 1]
            gains = lg[label[s:e].astype(np.int64)]
            order = np.argsort(-p[s:e], kind="mergesort")
            g_pred = gains[order]
            g_best = -np.sort(-gains)
            for k in ks:
                ideal = _dcg_at(g_best, k)
                results[k].append(1.0 if ideal <= 0 else _dcg_at(g_pred, k) / ideal)
        return [("%s@%d" % (self.name, k), float(np.mean(results[k]))) for k in ks]


class MapMetric(Metric):
    """MAP at eval_at positions (reference: map_metric.hpp)."""
    name = "map"
    greater_is_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        if query_boundaries is None:
            Log.fatal("[map]: query data required")
        ks = self.config.eval_at or [1, 2, 3, 4, 5]
        p = pred.ravel()
        qb = query_boundaries
        results = {k: [] for k in ks}
        for q in range(len(qb) - 1):
            s, e = qb[q], qb[q + 1]
            rel = (label[s:e] > 0).astype(np.float64)
            order = np.argsort(-p[s:e], kind="mergesort")
            rel = rel[order]
            hits = np.cumsum(rel)
            prec = hits / (np.arange(len(rel)) + 1.0)
            for k in ks:
                kk = min(k, len(rel))
                npos = rel[:kk].sum()
                results[k].append(0.0 if npos <= 0
                                  else float(np.sum(prec[:kk] * rel[:kk]) / npos))
        return [("%s@%d" % (self.name, k), float(np.mean(results[k]))) for k in ks]


_REGISTRY: Dict[str, Callable] = {
    "l1": L1Metric, "mean_absolute_error": L1Metric, "mae": L1Metric,
    "regression_l1": L1Metric,
    "l2": L2Metric, "mean_squared_error": L2Metric, "mse": L2Metric,
    "regression": L2Metric, "regression_l2": L2Metric,
    "rmse": RMSEMetric, "root_mean_squared_error": RMSEMetric, "l2_root": RMSEMetric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric, "mean_absolute_percentage_error": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "average_precision": AveragePrecisionMetric,
    "auc_mu": AucMuMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "cross_entropy": CrossEntropyMetric, "xentropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric, "xentlambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KullbackLeiblerMetric, "kldiv": KullbackLeiblerMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric, "rank_xendcg": NDCGMetric,
    "map": MapMetric, "mean_average_precision": MapMetric,
}

_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "quantile": "quantile", "mape": "mape", "gamma": "gamma",
    "tweedie": "tweedie", "binary": "binary_logloss", "multiclass": "multi_logloss",
    "multiclassova": "multi_logloss", "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "lambdarank": "ndcg",
    "rank_xendcg": "ndcg",
}


def create_metrics(config: Config, objective_name: str) -> List[Metric]:
    """Factory (reference: src/metric/metric.cpp:17). Empty metric config
    defaults to the objective's natural metric."""
    names = [m for m in config.metric if m not in ("", "null", "na", "none", "custom")]
    if not names:
        default = _DEFAULT_FOR_OBJECTIVE.get(objective_name)
        names = [default] if default else []
    out = []
    seen = set()
    for name in names:
        if name in seen:
            continue
        seen.add(name)
        if name not in _REGISTRY:
            Log.warning("Unknown metric: %s", name)
            continue
        out.append(_REGISTRY[name](config))
    return out
