"""Bounded labeled-traffic buffer for the online trainer.

Two windows over the same ingest stream:

- the **training buffer**: labeled rows accumulated since the last train
  cycle. Bounded by ``capacity_rows`` with drop-oldest semantics (a stale
  gradient signal is worth less than a fresh one, and an unbounded buffer
  under sustained overload is an OOM); ``take_training()`` drains it.
- the **shadow window**: a sliding window of the most recent labeled
  rows, NOT cleared by training — the promotion gate scores candidate
  vs. current model on it, so it must always reflect live traffic.

All methods are thread-safe (ingest arrives on HTTP handler threads, the
trainer drains from its worker thread).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Tuple

import numpy as np


class TrafficBuffer:
    """Bounded (X, y) chunk accumulator with a sliding shadow window."""

    def __init__(self, capacity_rows: int = 65536,
                 shadow_rows: int = 4096) -> None:
        if capacity_rows < 1:
            raise ValueError("capacity_rows must be >= 1")
        if shadow_rows < 1:
            raise ValueError("shadow_rows must be >= 1")
        self._lock = threading.Lock()
        self._cap = int(capacity_rows)
        self._shadow_cap = int(shadow_rows)
        self._chunks: deque = deque()        # pending training chunks
        self._rows = 0
        self._shadow: deque = deque()        # recent-traffic window
        self._shadow_held = 0
        self._dropped = 0
        self._total = 0

    # ------------------------------------------------------------- ingest
    def push(self, X, y, training: bool = True) -> int:
        """Append one labeled chunk; returns the buffered row count.
        Oldest training chunks are dropped once over capacity (a single
        chunk larger than the whole buffer is kept — it is the freshest
        data there is).

        ``training=False`` feeds ONLY the shadow window: fleet replay
        uses it for rows before the consumed-row watermark, which the
        restarted trainer must judge promotions on but must not train on
        a second time."""
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2:
            raise ValueError("rows must be 2-D (rows, features), got "
                             "ndim=%d" % X.ndim)
        y = np.ascontiguousarray(np.asarray(y, np.float64).ravel())
        if len(y) != X.shape[0]:
            raise ValueError("labels length %d != rows %d"
                             % (len(y), X.shape[0]))
        if len(y) == 0:
            with self._lock:
                return self._rows
        with self._lock:
            if training:
                self._chunks.append((X, y))
                self._rows += len(y)
                while self._rows > self._cap and len(self._chunks) > 1:
                    _, oy = self._chunks.popleft()
                    self._rows -= len(oy)
                    self._dropped += len(oy)
            self._total += len(y)
            self._shadow.append((X, y))
            self._shadow_held += len(y)
            while self._shadow_held > self._shadow_cap \
                    and len(self._shadow) > 1:
                _, oy = self._shadow.popleft()
                self._shadow_held -= len(oy)
            return self._rows

    # -------------------------------------------------------------- drain
    def take_training(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Drain the training buffer as one concatenated (X, y) pair, or
        None when empty. The shadow window is untouched."""
        with self._lock:
            if not self._chunks:
                return None
            chunks = list(self._chunks)
            self._chunks.clear()
            self._rows = 0
        if len(chunks) == 1:
            return chunks[0]
        return (np.concatenate([c[0] for c in chunks], axis=0),
                np.concatenate([c[1] for c in chunks]))

    def shadow(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Copy of the sliding recent-traffic window (X, y), or None if
        nothing was ever ingested."""
        with self._lock:
            chunks = list(self._shadow)
        if not chunks:
            return None
        if len(chunks) == 1:
            return chunks[0]
        return (np.concatenate([c[0] for c in chunks], axis=0),
                np.concatenate([c[1] for c in chunks]))

    def reset(self) -> None:
        """Forget everything (both windows, the drop/total counters). A
        standby trainer taking over a lease calls this before replaying
        the store, so the rebuilt state comes from the log alone."""
        with self._lock:
            self._chunks.clear()
            self._rows = 0
            self._shadow.clear()
            self._shadow_held = 0
            self._dropped = 0
            self._total = 0

    # --------------------------------------------------------------- state
    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def shadow_capacity(self) -> int:
        """The shadow window's row bound — also the compaction retention
        floor (``FleetStore.compact(keep_rows=...)``): retaining this
        many replayed rows is sufficient to rebuild the window
        bit-identically."""
        return self._shadow_cap

    @property
    def rows(self) -> int:
        """Rows currently buffered for the next train cycle."""
        with self._lock:
            return self._rows

    @property
    def shadow_rows(self) -> int:
        with self._lock:
            return self._shadow_held

    @property
    def dropped_rows(self) -> int:
        """Rows dropped (oldest-first) to stay under capacity."""
        with self._lock:
            return self._dropped

    @property
    def total_rows(self) -> int:
        """Rows ever ingested."""
        with self._lock:
            return self._total
