"""Continual refit with a shadow-scoring promotion gate.

:class:`OnlineTrainer` closes the loop the ROADMAP calls
"train-and-serve in one process": labeled traffic is ingested into a
bounded :class:`~lightgbm_tpu.online.buffer.TrafficBuffer`, a background
worker trains a CANDIDATE model off the serving thread — ``refit`` (leaf
values re-estimated on the frozen structure, the reference
GBDT::RefitTree contract) or ``continue`` (more boosting rounds via
``init_model``) — and the candidate is only promoted into the serving
booster if it shadow-scores at least as well as the incumbent on a
sliding window of recent live traffic.

Promotion is atomic: :meth:`GBDT.adopt` swaps the model list under the
booster's ``_cache_lock`` with a SINGLE version-token bump, so every
concurrent ``PredictSession`` snapshot sees the old ensemble or the new
one whole — never a half-committed pack. The displaced model is retained
as a rollback token (:meth:`OnlineTrainer.rollback`).

Telemetry: ``online/ingested_rows``, ``online/train_runs``,
``online/promotions``, ``online/rejections``, ``online/train_errors``
counters; ``online/train_ms``, ``online/shadow_ms``,
``online/promote_swap_ms`` histograms; ``online/train_cycle`` /
``online/shadow_score`` / ``online/promote`` spans in the flight
recorder (domain ``online`` records whenever the serve chain does).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np

from .. import obs
from ..obs import telemetry
from ..obs_trace import tracer
from ..utils.log import Log, LightGBMError
from .buffer import TrafficBuffer

MODES = ("refit", "continue")

#: floor for probabilities inside log-losses (reference binary_objective
#: uses a sigmoid that never saturates to exactly 0/1; host-side clipping
#: keeps a degenerate candidate finite instead of -inf)
_EPS = 1e-15


class _CandidateBuilder:
    """Thread-confined candidate factory for one train cycle.

    Holds a serialized snapshot of the serving model plus plain arrays;
    every object it builds (base booster, candidate, incumbent copy,
    datasets) is private to the worker's cycle — the cycle's only
    cross-thread surfaces are the trainer's lock-guarded snapshot cache
    and the guarded ``adopt`` that publishes the winner.
    graftlint models exactly this: calls on a freshly-constructed
    receiver do not propagate thread-reachability."""

    def __init__(self, mode: str, model_str: str,
                 train_params: Dict[str, Any], continue_rounds: int,
                 decay_rate: Optional[float],
                 shadow_decay: float = 1.0) -> None:
        self._mode = mode
        self._src = model_str
        self._params = dict(train_params)
        self._rounds = int(continue_rounds)
        self._decay = decay_rate
        self._shadow_decay = float(shadow_decay)

    def build(self, X: np.ndarray, y: np.ndarray):
        """Train the candidate: leaf re-estimation on the frozen
        structure (``refit``, the reference GBDT::RefitTree contract) or
        more boosting rounds from the snapshot (``continue``)."""
        from ..basic import Booster, Dataset
        base = Booster(model_str=self._src)
        if self._mode == "refit":
            return base.refit(X, y, decay_rate=self._decay)
        from ..engine import train as _train
        return _train(self._params, Dataset(X, label=y),
                      num_boost_round=self._rounds, init_model=base)

    def serialize(self, candidate) -> str:
        """Candidate's model string (the next cycle's snapshot when this
        one wins promotion). Runs here, not in the trainer, so the
        serialization stays on the worker's private objects."""
        return candidate.model_to_string()

    def score_pair(self, candidate, X: np.ndarray,
                   y: np.ndarray) -> tuple:
        """(incumbent_loss, candidate_loss) on the shadow window. The
        incumbent is scored as a private copy of the snapshot so shadow
        scoring never contends with live serving dispatches."""
        from ..basic import Booster
        incumbent = Booster(model_str=self._src)
        w = None
        if self._shadow_decay < 1.0:
            # shadow rows arrive oldest -> newest (TrafficBuffer.shadow):
            # the newest row carries weight 1 and every step back decays,
            # so live drift dominates the promotion verdict
            w = self._shadow_decay ** np.arange(len(y) - 1, -1, -1,
                                                dtype=np.float64)
        return self._loss(incumbent, X, y, w), self._loss(candidate, X, y, w)

    def _loss(self, model, X: np.ndarray, y: np.ndarray,
              w: Optional[np.ndarray] = None) -> float:
        """Objective-matched (weighted) mean loss: logloss for binary,
        multi-logloss for multiclass, MSE otherwise (predictions come back
        transformed, so probabilities are directly comparable)."""
        pred = np.asarray(model.predict(X), np.float64)
        obj = getattr(model.inner.objective, "name", "") \
            if model.inner.objective is not None else ""
        n = len(y)
        if obj == "binary":
            p = np.clip(pred.ravel(), _EPS, 1.0 - _EPS)
            per_row = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        elif obj.startswith("multiclass"):
            p = pred.reshape(n, -1)
            picked = p[np.arange(n), y.astype(np.int64)]
            per_row = -np.log(np.clip(picked, _EPS, 1.0))
        else:
            per_row = (pred.ravel() - y) ** 2
        return float(np.average(per_row, weights=w))


class OnlineTrainer:
    """Background continual-training loop over one serving booster.

    ``booster`` is the live ``lgb.Booster`` the serving sessions hold;
    promotions mutate it in place (atomically) so every
    ``PredictSession``/``MicroBatcher`` over it picks the new model up on
    its next dispatch without reconnecting anything.

    With ``start=True`` (default) a named daemon worker thread watches
    the buffer and trains whenever ``trigger_rows`` rows accumulated (or
    ``trigger_interval_s`` elapsed with at least ``min_rows`` buffered).
    Tests drive the same cycle synchronously via :meth:`run_once` with
    ``start=False``.
    """

    def __init__(self, booster, *, mode: str = "refit",
                 trigger_rows: int = 2048,
                 trigger_interval_s: float = 0.0,
                 buffer_rows: int = 65536, shadow_rows: int = 4096,
                 promote_threshold: float = 1.0, min_rows: int = 64,
                 continue_rounds: int = 10,
                 continue_params: Optional[Dict[str, Any]] = None,
                 decay_rate: Optional[float] = None,
                 shadow_decay: float = 1.0,
                 candidate_factory=None,
                 start: bool = True) -> None:
        if mode not in MODES:
            raise LightGBMError("online mode must be one of %s, got %r"
                                % ("|".join(MODES), mode))
        if not 0.0 < float(shadow_decay) <= 1.0:
            raise LightGBMError("online shadow_decay must be in (0, 1], "
                                "got %g" % shadow_decay)
        if not hasattr(booster, "refit") or not hasattr(booster, "inner"):
            raise LightGBMError(
                "OnlineTrainer needs a lightgbm_tpu.Booster (refit and "
                "adopt live on the Booster API)")
        if trigger_rows < 1:
            raise LightGBMError("online trigger_rows must be >= 1")
        if promote_threshold < 0:
            raise LightGBMError("online promote_threshold must be >= 0")
        self._booster = booster
        self._mode = mode
        self._trigger_rows = int(trigger_rows)
        self._interval = float(trigger_interval_s)
        self._min_rows = max(1, int(min_rows))
        self._threshold = float(promote_threshold)
        self._continue_rounds = int(continue_rounds)
        self._decay = decay_rate
        self._shadow_decay = float(shadow_decay)
        # test/extension hook: a callable (X, y) -> Booster replaces the
        # default candidate build (degraded-candidate gate tests)
        self._candidate_factory = candidate_factory
        # continue-mode params frozen here (main thread) so the worker
        # never reads live config off the shared booster
        cfg = getattr(booster, "config", None)
        params: Dict[str, Any] = {"verbosity": -1}
        if cfg is not None:
            params.update(objective=cfg.objective, num_class=cfg.num_class,
                          learning_rate=cfg.learning_rate,
                          num_leaves=cfg.num_leaves, max_bin=cfg.max_bin)
        params.update(continue_params or {})
        self._train_params = params
        # serving-model snapshot cache: serialized HERE (main thread,
        # before the worker exists) and thereafter only updated at
        # promotion/rollback from strings the worker computed on its own
        # private candidate. The worker never serializes the live
        # booster, so its only shared-model calls are the lock-guarded
        # adopt/restore swaps. Contract: the trainer is the sole mutator
        # of the served model after start — training the live booster
        # externally desyncs this snapshot.
        self._model_str = booster.model_to_string()
        self.buffer = TrafficBuffer(buffer_rows, shadow_rows)
        # Condition doubles as the state lock (counters, last-result
        # strings, the rollback token) and the worker's wakeup: ingest
        # notifies when a trigger is reached, close notifies to stop.
        self._lock = threading.Condition()
        self._stopped = False
        self._trains = 0
        self._promotions = 0
        self._rejections = 0
        self._errors = 0
        self._last_result = "idle"
        self._last_error = ""
        self._last_losses: Optional[Dict[str, float]] = None
        self._rollback: Optional[tuple] = None
        self._last_train_t = obs.monotonic()
        # pre-touch the promotion counters so a freshly-started online
        # server exposes the whole family on /metrics before the first
        # train cycle (dashboards key on the series existing)
        telemetry.count("online/promotions", 0)
        telemetry.count("online/rejections", 0)
        telemetry.count("online/train_runs", 0)
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._worker, name="lgbtpu-online-trainer",
                daemon=True)
            self._thread.start()

    # --------------------------------------------------------------- ingest
    def ingest(self, X, y) -> int:
        """Add labeled rows (features, labels) to the training buffer and
        shadow window; returns the buffered row count. Called from HTTP
        handler threads (POST /ingest) or embedding code; never blocks on
        training."""
        y_arr = np.asarray(y, np.float64).ravel()
        buffered = self.buffer.push(X, y_arr)
        telemetry.count("online/ingested_rows", int(y_arr.size))
        telemetry.gauge("online/buffered_rows", buffered)
        if buffered >= self._trigger_rows:
            with self._lock:
                self._lock.notify_all()
        return buffered

    # --------------------------------------------------------------- worker
    def _worker(self) -> None:
        # poll granularity: the interval trigger when set, else a coarse
        # tick — row triggers arrive via notify so the tick only bounds
        # shutdown latency
        poll = self._interval if self._interval > 0 else 0.5
        while True:
            with self._lock:
                if self._stopped:
                    return
                self._lock.wait(timeout=poll)
                if self._stopped:
                    return
            if self._should_train():
                try:
                    self.run_once()
                except BaseException as exc:
                    # a failed train cycle must never take serving down:
                    # record, count, keep looping
                    telemetry.count("online/train_errors")
                    with self._lock:
                        self._errors += 1
                        self._last_error = "%s: %s" % (type(exc).__name__,
                                                       exc)
                    Log.warning("online: train cycle failed: %s: %s",
                                type(exc).__name__, exc)

    def _should_train(self) -> bool:
        rows = self.buffer.rows
        if rows >= self._trigger_rows:
            return True
        if self._interval > 0 and rows >= self._min_rows:
            with self._lock:
                last = self._last_train_t
            return obs.monotonic() - last >= self._interval
        return False

    # ---------------------------------------------------------------- cycle
    def run_once(self) -> str:
        """One synchronous train cycle: drain the buffer, build a
        candidate, shadow-score it, promote or reject. Returns
        ``"promoted"``, ``"rejected"`` or ``"skipped"`` (not enough
        data). Tests call this directly with ``start=False``."""
        with self._lock:
            self._last_train_t = obs.monotonic()
        data = self.buffer.take_training()
        if data is None or len(data[1]) < self._min_rows:
            if data is not None:
                # not enough signal yet — put it back for the next cycle
                self.buffer.push(data[0], data[1])
            self._finish("skipped", None)
            return "skipped"
        X, y = data
        with tracer.span("online/train_cycle", domain="online",
                         rows=int(len(y)), mode=self._mode):
            telemetry.count("online/train_runs")
            telemetry.count("online/trained_rows", int(len(y)))
            with self._lock:
                self._trains += 1
            # snapshot of the serving model, maintained across
            # promotions/rollbacks — everything downstream is private to
            # the builder until the guarded adopt publishes the winner
            with self._lock:
                src = self._model_str
            builder = _CandidateBuilder(self._mode, src,
                                        self._train_params,
                                        self._continue_rounds, self._decay,
                                        self._shadow_decay)
            with telemetry.timed_observe("online/train_ms"), \
                    tracer.span("online/train", domain="online"):
                candidate = (self._candidate_factory(X, y)
                             if self._candidate_factory is not None
                             else builder.build(X, y))
            accept, losses = False, None
            shadow = self.buffer.shadow()
            if shadow is not None:  # no traffic to judge on => reject
                Xs, ys = shadow
                with telemetry.timed_observe("online/shadow_ms"), \
                        tracer.span("online/shadow_score", domain="online",
                                    rows=int(len(ys))):
                    cur, cand = builder.score_pair(candidate, Xs, ys)
                losses = {"current": float(cur), "candidate": float(cand),
                          "threshold": self._threshold,
                          "rows": int(len(ys))}
                accept = bool(np.isfinite(cand)
                              and cand <= self._threshold * cur + 1e-12)
            if accept:
                self._promote(candidate, builder.serialize(candidate), src)
                self._finish("promoted", losses)
                return "promoted"
            telemetry.count("online/rejections")
            with self._lock:
                self._rejections += 1
            self._finish("rejected", losses)
            return "rejected"

    # ------------------------------------------------------------ promotion
    def _promote(self, candidate, cand_str: str, prev_str: str) -> None:
        with telemetry.timed_observe("online/promote_swap_ms"), \
                tracer.span("online/promote", domain="online"):
            token = self._booster.adopt(candidate)
        with self._lock:
            # rollback token carries the displaced model's string so the
            # snapshot cache rewinds with the swap
            self._rollback = (token, prev_str)
            self._model_str = cand_str
            self._promotions += 1
        telemetry.count("online/promotions")
        telemetry.gauge("online/model_version",
                        self._booster.inner.model_version)

    def rollback(self) -> bool:
        """Restore the model displaced by the last promotion (single
        atomic swap, like the promotion itself). Returns False when
        there is nothing to roll back to."""
        with self._lock:
            tok = self._rollback
            self._rollback = None
        if tok is None:
            return False
        snapshot, prev_str = tok
        self._booster.restore(snapshot)
        with self._lock:
            self._model_str = prev_str
        telemetry.count("online/rollbacks")
        return True

    def _finish(self, result: str, losses) -> None:
        with self._lock:
            self._last_result = result
            if losses is not None:
                self._last_losses = losses

    # ----------------------------------------------------------------- state
    def state(self) -> Dict[str, Any]:
        """JSON-serializable trainer state (surfaced on /healthz)."""
        with self._lock:
            st = {
                "running": self._thread.is_alive()
                if self._thread is not None else False,
                "mode": self._mode,
                "trigger_rows": self._trigger_rows,
                "shadow_decay": self._shadow_decay,
                "trains": self._trains,
                "promotions": self._promotions,
                "rejections": self._rejections,
                "errors": self._errors,
                "last_result": self._last_result,
                "last_error": self._last_error,
                "last_losses": self._last_losses,
                "can_rollback": self._rollback is not None,
            }
        st["buffered_rows"] = self.buffer.rows
        st["shadow_rows"] = self.buffer.shadow_rows
        st["dropped_rows"] = self.buffer.dropped_rows
        st["total_ingested_rows"] = self.buffer.total_rows
        st["model_version"] = self._booster.inner.model_version
        return st

    # -------------------------------------------------------------- shutdown
    def close(self, timeout: Optional[float] = None) -> None:
        """Stop the worker (the in-flight cycle finishes). Idempotent."""
        with self._lock:
            self._stopped = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "OnlineTrainer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
