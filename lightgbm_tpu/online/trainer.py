"""Continual refit with a shadow-scoring promotion gate.

:class:`OnlineTrainer` closes the loop the ROADMAP calls
"train-and-serve in one process": labeled traffic is ingested into a
bounded :class:`~lightgbm_tpu.online.buffer.TrafficBuffer`, a background
worker trains a CANDIDATE model off the serving thread — ``refit`` (leaf
values re-estimated on the frozen structure, the reference
GBDT::RefitTree contract) or ``continue`` (more boosting rounds via
``init_model``) — and the candidate is only promoted into the serving
booster if it shadow-scores at least as well as the incumbent on a
sliding window of recent live traffic.

Promotion is atomic: :meth:`GBDT.adopt` swaps the model list under the
booster's ``_cache_lock`` with a SINGLE version-token bump, so every
concurrent ``PredictSession`` snapshot sees the old ensemble or the new
one whole — never a half-committed pack. The displaced model is retained
as a rollback token (:meth:`OnlineTrainer.rollback`).

Fleet extensions (PR 11), all off by default:

- **Hysteresis** (``promote_patience``): a candidate must win K
  CONSECUTIVE shadow evaluations before the swap happens — one lucky
  window on drifting traffic no longer flips the serving model
  (``run_once`` returns ``"deferred"`` for intermediate wins).
- **Auto-rollback** (``rollback_threshold``): after a promotion the
  trainer keeps the displaced model string and watches traffic ingested
  AFTER the swap; once ``rollback_min_rows`` fresh labeled rows arrive it
  scores promoted vs. displaced on them and rolls back automatically if
  the promoted model's live loss exceeds ``rollback_threshold`` x the
  displaced model's. The shadow gate judges the PAST; this watch judges
  the future the gate could not see.
- **Durability** (``store``): a :class:`~lightgbm_tpu.fleet.FleetStore`
  persists every ingest chunk, every gate verdict (with the
  consecutive-win counter and a consumed-row watermark) and publishes
  every promotion/rollback as a version-tokened whole-model artifact. On
  boot the trainer replays the store: rows at or below the watermark
  re-enter ONLY the shadow window (already trained — replaying them into
  the training buffer would double-train), rows above it re-enter both,
  and the hysteresis win-streak resumes where the dead process left it.

Failover (PR 13), also off by default: with ``lease_ttl_s`` > 0 the
trainer starts in STANDBY — it persists ingest but neither buffers nor
trains — until it wins the store's trainer lease
(:meth:`~lightgbm_tpu.fleet.store.FleetStore.acquire_lease`). On
acquisition it arms publish fencing with its lease epoch, rebuilds its
state through the replay-on-boot path (so a standby taking over a dead
holder resumes the identical watermark/win-streak), and goes active;
the worker then heartbeats the lease every ttl/3 and demotes itself
back to standby the moment a renewal fails — from which point the
fencing epoch guarantees its publishes are rejected even if it believes
it is still primary. ``compact_bytes`` > 0 additionally compacts the
store (snapshot + truncate, ``FleetStore.compact``) whenever the event
log outgrows that bound, after the gate verdict that made the state
durable.

Telemetry: ``online/ingested_rows``, ``online/train_runs``,
``online/promotions``, ``online/rejections``, ``online/train_errors``
counters; ``online/train_ms``, ``online/shadow_ms``,
``online/promote_swap_ms`` histograms; ``online/train_cycle`` /
``online/shadow_score`` / ``online/promote`` spans in the flight
recorder (domain ``online`` records whenever the serve chain does).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..obs import telemetry
from ..obs_trace import tracer
from ..utils.log import Log, LightGBMError
from .buffer import TrafficBuffer

MODES = ("refit", "continue")

#: floor for probabilities inside log-losses (reference binary_objective
#: uses a sigmoid that never saturates to exactly 0/1; host-side clipping
#: keeps a degenerate candidate finite instead of -inf)
_EPS = 1e-15


def _objective_loss(model, X: np.ndarray, y: np.ndarray,
                    w: Optional[np.ndarray] = None) -> float:
    """Objective-matched (weighted) mean loss: logloss for binary,
    multi-logloss for multiclass, MSE otherwise (predictions come back
    transformed, so probabilities are directly comparable). Shared by the
    shadow gate and the post-promotion live watch — both must judge by
    the same yardstick or a promotion could pass one and fail the
    other on scale alone."""
    pred = np.asarray(model.predict(X), np.float64)
    obj = getattr(model.inner.objective, "name", "") \
        if model.inner.objective is not None else ""
    n = len(y)
    if obj == "binary":
        p = np.clip(pred.ravel(), _EPS, 1.0 - _EPS)
        per_row = -(y * np.log(p) + (1 - y) * np.log(1 - p))
    elif obj.startswith("multiclass"):
        p = pred.reshape(n, -1)
        picked = p[np.arange(n), y.astype(np.int64)]
        per_row = -np.log(np.clip(picked, _EPS, 1.0))
    else:
        per_row = (pred.ravel() - y) ** 2
    return float(np.average(per_row, weights=w))


class _CandidateBuilder:
    """Thread-confined candidate factory for one train cycle.

    Holds a serialized snapshot of the serving model plus plain arrays;
    every object it builds (base booster, candidate, incumbent copy,
    datasets) is private to the worker's cycle — the cycle's only
    cross-thread surfaces are the trainer's lock-guarded snapshot cache
    and the guarded ``adopt`` that publishes the winner.
    graftlint models exactly this: calls on a freshly-constructed
    receiver do not propagate thread-reachability."""

    def __init__(self, mode: str, model_str: str,
                 train_params: Dict[str, Any], continue_rounds: int,
                 decay_rate: Optional[float],
                 shadow_decay: float = 1.0) -> None:
        self._mode = mode
        self._src = model_str
        self._params = dict(train_params)
        self._rounds = int(continue_rounds)
        self._decay = decay_rate
        self._shadow_decay = float(shadow_decay)

    def build(self, X: np.ndarray, y: np.ndarray):
        """Train the candidate: leaf re-estimation on the frozen
        structure (``refit``, the reference GBDT::RefitTree contract) or
        more boosting rounds from the snapshot (``continue``)."""
        from ..basic import Booster, Dataset
        base = Booster(model_str=self._src)
        if self._mode == "refit":
            return base.refit(X, y, decay_rate=self._decay)
        from ..engine import train as _train
        return _train(self._params, Dataset(X, label=y),
                      num_boost_round=self._rounds, init_model=base)

    def serialize(self, candidate) -> str:
        """Candidate's model string (the next cycle's snapshot when this
        one wins promotion). Runs here, not in the trainer, so the
        serialization stays on the worker's private objects."""
        return candidate.model_to_string()

    def score_pair(self, candidate, X: np.ndarray,
                   y: np.ndarray) -> tuple:
        """(incumbent_loss, candidate_loss) on the shadow window. The
        incumbent is scored as a private copy of the snapshot so shadow
        scoring never contends with live serving dispatches."""
        from ..basic import Booster
        incumbent = Booster(model_str=self._src)
        w = None
        if self._shadow_decay < 1.0:
            # shadow rows arrive oldest -> newest (TrafficBuffer.shadow):
            # the newest row carries weight 1 and every step back decays,
            # so live drift dominates the promotion verdict
            w = self._shadow_decay ** np.arange(len(y) - 1, -1, -1,
                                                dtype=np.float64)
        return (_objective_loss(incumbent, X, y, w),
                _objective_loss(candidate, X, y, w))


class _WatchScorer:
    """Thread-confined scorer for one live-watch verdict.

    Same confinement contract as :class:`_CandidateBuilder`: constructed
    fresh per evaluation from serialized model strings, so the boosters
    it builds and scores are private to that call — graftlint's
    thread-reachability stops at a freshly-constructed receiver, keeping
    the predict internals out of the worker thread's shared-state
    closure."""

    def __init__(self, cand_str: str, prev_str: str) -> None:
        self._cand = cand_str
        self._prev = prev_str

    def losses(self, X: np.ndarray, y: np.ndarray) -> tuple:
        """(promoted_loss, displaced_loss) on the post-swap traffic."""
        from ..basic import Booster
        promoted = Booster(model_str=self._cand)
        displaced = Booster(model_str=self._prev)
        return (_objective_loss(promoted, X, y),
                _objective_loss(displaced, X, y))


class OnlineTrainer:
    """Background continual-training loop over one serving booster.

    ``booster`` is the live ``lgb.Booster`` the serving sessions hold;
    promotions mutate it in place (atomically) so every
    ``PredictSession``/``MicroBatcher`` over it picks the new model up on
    its next dispatch without reconnecting anything.

    With ``start=True`` (default) a named daemon worker thread watches
    the buffer and trains whenever ``trigger_rows`` rows accumulated (or
    ``trigger_interval_s`` elapsed with at least ``min_rows`` buffered).
    Tests drive the same cycle synchronously via :meth:`run_once` with
    ``start=False``.
    """

    def __init__(self, booster, *, mode: str = "refit",
                 trigger_rows: int = 2048,
                 trigger_interval_s: float = 0.0,
                 buffer_rows: int = 65536, shadow_rows: int = 4096,
                 promote_threshold: float = 1.0, min_rows: int = 64,
                 continue_rounds: int = 10,
                 continue_params: Optional[Dict[str, Any]] = None,
                 decay_rate: Optional[float] = None,
                 shadow_decay: float = 1.0,
                 promote_patience: int = 1,
                 rollback_threshold: float = 0.0,
                 rollback_min_rows: int = 64,
                 store=None, replay: bool = True,
                 lease_ttl_s: float = 0.0,
                 holder_id: Optional[str] = None,
                 compact_bytes: int = 0,
                 keep_artifacts: int = 0,
                 snapshot_rows: int = 0,
                 heartbeat_interval_s: float = 0.0,
                 advertise_url: Optional[str] = None,
                 candidate_factory=None,
                 start: bool = True) -> None:
        if mode not in MODES:
            raise LightGBMError("online mode must be one of %s, got %r"
                                % ("|".join(MODES), mode))
        if not 0.0 < float(shadow_decay) <= 1.0:
            raise LightGBMError("online shadow_decay must be in (0, 1], "
                                "got %g" % shadow_decay)
        if promote_patience < 1:
            raise LightGBMError("online promote_patience must be >= 1, "
                                "got %d" % promote_patience)
        if rollback_threshold < 0:
            raise LightGBMError("online rollback_threshold must be >= 0 "
                                "(0 disables the live watch), got %g"
                                % rollback_threshold)
        if rollback_min_rows < 1:
            raise LightGBMError("online rollback_min_rows must be >= 1")
        if not hasattr(booster, "refit") or not hasattr(booster, "inner"):
            raise LightGBMError(
                "OnlineTrainer needs a lightgbm_tpu.Booster (refit and "
                "adopt live on the Booster API)")
        if trigger_rows < 1:
            raise LightGBMError("online trigger_rows must be >= 1")
        if promote_threshold < 0:
            raise LightGBMError("online promote_threshold must be >= 0")
        if lease_ttl_s < 0:
            raise LightGBMError("online lease_ttl_s must be >= 0 "
                                "(0 disables failover leasing), got %g"
                                % lease_ttl_s)
        if compact_bytes < 0 or keep_artifacts < 0:
            raise LightGBMError("online compact_bytes/keep_artifacts "
                                "must be >= 0")
        if snapshot_rows < 0:
            raise LightGBMError("online snapshot_rows must be >= 0 "
                                "(0 disables snapshot compaction), got %d"
                                % snapshot_rows)
        if snapshot_rows > 0 and store is None:
            raise LightGBMError("online snapshot_rows needs a fleet "
                                "store to snapshot into")
        if heartbeat_interval_s < 0:
            raise LightGBMError("online heartbeat_interval_s must be "
                                ">= 0 (0 disables heartbeats), got %g"
                                % heartbeat_interval_s)
        if lease_ttl_s > 0 and store is None:
            raise LightGBMError("online lease_ttl_s needs a fleet store "
                                "to hold the lease in")
        if compact_bytes > 0 and store is None:
            raise LightGBMError("online compact_bytes needs a fleet "
                                "store to compact")
        self._booster = booster
        self._mode = mode
        self._trigger_rows = int(trigger_rows)
        self._interval = float(trigger_interval_s)
        self._min_rows = max(1, int(min_rows))
        self._threshold = float(promote_threshold)
        self._continue_rounds = int(continue_rounds)
        self._decay = decay_rate
        self._shadow_decay = float(shadow_decay)
        self._patience = int(promote_patience)
        self._rb_threshold = float(rollback_threshold)
        self._rb_min_rows = int(rollback_min_rows)
        # the fleet store is duck-typed (append_ingest/append_gate/
        # publish/events, plus acquire/renew/release_lease + compact when
        # the failover/retention knobs are on) so the trainer stays
        # importable without the fleet package and tests can inject fakes
        self._store = store
        self._lease_ttl = float(lease_ttl_s)
        self._holder = str(holder_id) if holder_id \
            else "pid-%d" % os.getpid()
        self._compact_bytes = int(compact_bytes)
        self._keep_artifacts = int(keep_artifacts)
        self._snapshot_rows = int(snapshot_rows)
        # control plane: the URL this trainer's serving endpoint is
        # reachable at, advertised in the lease record at acquire/renew
        # time — the leader_hint ingest forwarding follows. Public and
        # mutable: a server bound to an ephemeral port learns its
        # address after the trainer exists, and the next renew
        # advertises it.
        self.advertise_url = str(advertise_url) if advertise_url else None
        self._replay_on_acquire = bool(replay)
        # test/extension hook: a callable (X, y) -> Booster replaces the
        # default candidate build (degraded-candidate gate tests)
        self._candidate_factory = candidate_factory
        # continue-mode params frozen here (main thread) so the worker
        # never reads live config off the shared booster
        cfg = getattr(booster, "config", None)
        params: Dict[str, Any] = {"verbosity": -1}
        if cfg is not None:
            params.update(objective=cfg.objective, num_class=cfg.num_class,
                          learning_rate=cfg.learning_rate,
                          num_leaves=cfg.num_leaves, max_bin=cfg.max_bin)
        params.update(continue_params or {})
        self._train_params = params
        # serving-model snapshot cache: serialized HERE (main thread,
        # before the worker exists) and thereafter only updated at
        # promotion/rollback from strings the worker computed on its own
        # private candidate. The worker never serializes the live
        # booster, so its only shared-model calls are the lock-guarded
        # adopt/restore swaps. Contract: the trainer is the sole mutator
        # of the served model after start — training the live booster
        # externally desyncs this snapshot.
        self._model_str = booster.model_to_string()
        self.buffer = TrafficBuffer(buffer_rows, shadow_rows)
        # Condition doubles as the state lock (counters, last-result
        # strings, the rollback token) and the worker's wakeup: ingest
        # notifies when a trigger is reached, close notifies to stop.
        self._lock = threading.Condition()
        self._stopped = False
        self._trains = 0
        self._promotions = 0
        self._rejections = 0
        self._errors = 0
        self._last_result = "idle"
        self._last_error = ""
        self._last_losses: Optional[Dict[str, float]] = None
        self._rollback: Optional[tuple] = None
        self._last_train_t = obs.monotonic()
        # hysteresis win-streak, consumed-row watermark (rows drained
        # into a train cycle — the replay boundary between shadow-only
        # and trainable traffic) and the post-promotion live watch
        self._wins = 0
        self._consumed_rows = 0
        self._replayed_rows = 0
        self._auto_rollbacks = 0
        self._last_promotion_ts = 0.0
        self._last_rollback_ts = 0.0
        self._watch: Optional[Dict[str, Any]] = None
        self._watch_chunks: List[Tuple[np.ndarray, np.ndarray]] = []
        # failover: with a lease ttl the trainer boots in STANDBY (no
        # replay, no training) until it wins the lease — try_acquire()
        # then replays and goes active with fencing armed
        self._standby = self._lease_ttl > 0
        self._lease_epoch = 0
        self._lease_lost = 0
        self._last_renew_t = obs.monotonic()
        # fleet federation: periodic heartbeats into the store's sidecar
        # (role/version/lease/counters) for the /fleet/status rollup
        self._hb_interval = float(heartbeat_interval_s)
        self._hb_last = 0.0
        self._hb_sent = 0
        self._hb_errors = 0
        if self._store is not None and replay and not self._standby:
            self._replay()
        # pre-touch the promotion counters so a freshly-started online
        # server exposes the whole family on /metrics before the first
        # train cycle (dashboards key on the series existing)
        telemetry.count("online/promotions", 0)
        telemetry.count("online/rejections", 0)
        telemetry.count("online/train_runs", 0)
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._worker, name="lgbtpu-online-trainer",
                daemon=True)
            self._thread.start()

    # --------------------------------------------------------------- ingest
    def ingest(self, X, y) -> int:
        """Add labeled rows (features, labels) to the training buffer and
        shadow window; returns the buffered row count. Called from HTTP
        handler threads (POST /ingest) or embedding code; never blocks on
        training.

        With a fleet store the chunk is persisted BEFORE the in-memory
        push — a crash after the append replays the chunk on restart
        instead of losing it; a crash before it loses a chunk the caller
        never saw acknowledged."""
        X_arr = np.asarray(X, np.float64)
        y_arr = np.asarray(y, np.float64).ravel()
        if self._store is not None:
            self._store.append_ingest(X_arr, y_arr)
        with self._lock:
            standby = self._standby
        if standby:
            # a standby must not accumulate local state: on takeover it
            # rebuilds everything from the log (which just got this
            # chunk), so buffering here would double-count it
            telemetry.count("online/ingested_rows", int(y_arr.size))
            return 0
        buffered = self.buffer.push(X_arr, y_arr)
        self._feed_watch(X_arr, y_arr)
        telemetry.count("online/ingested_rows", int(y_arr.size))
        telemetry.gauge("online/buffered_rows", buffered)
        if buffered >= self._trigger_rows:
            with self._lock:
                self._lock.notify_all()
        return buffered

    def _feed_watch(self, X: np.ndarray, y: np.ndarray) -> None:
        """Route fresh post-promotion traffic into the live watch (the
        rollback verdict must come from rows the promoted model is
        actually serving, not from the shadow window the gate already
        judged)."""
        with self._lock:
            watch = self._watch
            if watch is None or watch["rows"] >= self._rb_min_rows:
                return
            if X.ndim == 1:
                X = X[None, :]
            self._watch_chunks.append((X, y))
            watch["rows"] += int(len(y))
            armed = watch["rows"] >= self._rb_min_rows
        if armed:
            with self._lock:
                self._lock.notify_all()

    # --------------------------------------------------------------- replay
    def _replay(self) -> None:
        """Rebuild buffer + hysteresis state from the fleet store.

        Gate events carry the consumed-row watermark: ingest rows at or
        below it were already drained into a train cycle by the dead
        process, so they re-enter ONLY the shadow window (training on
        them again would double-count their gradient signal); rows above
        it re-enter the training buffer too. The win-streak resumes from
        the newest gate event.

        A ``compact`` record stands in for everything truncated before
        it: its watermark/wins snapshot seeds the gate fold, and its
        ``row_base`` seeds the global row offset so the retained ingest
        suffix replays at the same offsets it originally held — replay
        from a compacted log is bit-identical to the full log (pinned in
        tests/test_failover.py)."""
        events = list(self._store.events())
        watermark = 0
        wins = 0
        for e in events:
            kind = e.get("kind")
            if kind == "compact":
                watermark = max(watermark, int(e.get("watermark", 0)))
                wins = int(e.get("wins", 0))
            elif kind == "gate":
                watermark = max(watermark, int(e.get("consumed_rows", 0)))
                wins = int(e.get("wins", 0))
        with self._lock:
            self._wins = wins
        seen = 0
        replayed = 0

        def push_chunk(lo: int, e: Dict[str, Any]) -> int:
            try:
                X = np.asarray(e["rows"], np.float64)
                y = np.asarray(e["labels"], np.float64).ravel()
            except (KeyError, TypeError, ValueError):
                return 0   # a malformed entry must not block the boot
            if X.ndim == 1:
                X = X[None, :]
            if len(y) == 0 or X.shape[0] != len(y):
                return 0
            hi = lo + len(y)
            if hi <= watermark:
                self.buffer.push(X, y, training=False)
            elif lo >= watermark:
                self.buffer.push(X, y)
            else:
                # chunk straddles the watermark: split it so only the
                # untrained tail re-enters the training buffer
                cut = watermark - lo
                self.buffer.push(X[:cut], y[:cut], training=False)
                self.buffer.push(X[cut:], y[cut:])
            return len(y)

        for e in events:
            kind = e.get("kind")
            if kind == "compact":
                if isinstance(e.get("snapshot"), dict):
                    # snapshot bootstrap: the record's row_base already
                    # sits PAST the snapshotted span, so its chunks are
                    # pushed here at their recorded offsets (one blob
                    # read instead of per-chunk log lines); a missing
                    # or corrupt snapshot degrades to zero chunks with
                    # offsets intact — lost buffer warmth, never a
                    # misaligned replay
                    loader = getattr(self._store, "snapshot_chunks",
                                     None)
                    if loader is not None:
                        for lo, _hi, ev in loader(e):
                            replayed += push_chunk(lo, ev)
                seen = max(seen, int(e.get("row_base", 0)))
                continue
            if kind != "ingest":
                continue
            n = push_chunk(seen, e)
            seen += n
            replayed += n
        with self._lock:
            self._consumed_rows = min(watermark, seen)
            self._replayed_rows = replayed
            wins_now = self._wins
        if replayed:
            telemetry.count("fleet/replayed_rows", replayed)
            Log.info("fleet: replayed %d ingest rows (%d shadow-only at "
                     "watermark %d), win-streak=%d", replayed,
                     min(watermark, seen), watermark, wins_now)

    # --------------------------------------------------------------- worker
    def _worker(self) -> None:
        # poll granularity: the interval trigger when set, else a coarse
        # tick — row triggers arrive via notify so the tick only bounds
        # shutdown latency
        poll = self._interval if self._interval > 0 else 0.5
        if self._lease_ttl > 0:
            # the heartbeat must fire well inside the ttl no matter how
            # coarse the train trigger is
            poll = min(poll, self._lease_ttl / 3.0)
        while True:
            with self._lock:
                if self._stopped:
                    return
                self._lock.wait(timeout=poll)
                if self._stopped:
                    return
            active = self._lease_ttl <= 0 or self._lease_tick()
            # standbys heartbeat too: the /fleet/status rollup must show
            # the warm spare waiting on the lease, not just the holder
            self.maybe_heartbeat()
            if not active:
                continue   # standby (or just demoted): no watch, no train
            try:
                # the live watch outranks training: a regressed model
                # should be rolled back before another cycle builds a
                # candidate on top of it
                self.watch_once()
            except BaseException as exc:
                telemetry.count("online/train_errors")
                with self._lock:
                    self._errors += 1
                    self._last_error = "%s: %s" % (type(exc).__name__, exc)
                Log.warning("online: live watch failed: %s: %s",
                            type(exc).__name__, exc)
            if self._should_train():
                try:
                    self.run_once()
                except BaseException as exc:
                    # a failed train cycle must never take serving down:
                    # record, count, keep looping
                    telemetry.count("online/train_errors")
                    with self._lock:
                        self._errors += 1
                        self._last_error = "%s: %s" % (type(exc).__name__,
                                                       exc)
                    Log.warning("online: train cycle failed: %s: %s",
                                type(exc).__name__, exc)

    def _should_train(self) -> bool:
        rows = self.buffer.rows
        if rows >= self._trigger_rows:
            return True
        if self._interval > 0 and rows >= self._min_rows:
            with self._lock:
                last = self._last_train_t
            return obs.monotonic() - last >= self._interval
        return False

    # --------------------------------------------------------------- failover
    def try_acquire(self) -> bool:
        """One lease-acquisition attempt. On success: arm publish
        fencing with the new epoch, rebuild state from the log through
        the replay path (the identical watermark/win-streak the dead
        holder had made durable), go active. Returns True when this
        trainer is (now) the active publisher. Trivially True when
        leasing is off."""
        if self._lease_ttl <= 0:
            return True
        with self._lock:
            if not self._standby:
                return True
        try:
            # url= only when advertised: fake stores in tests (and real
            # ones predating the control plane) take two positionals
            if self.advertise_url:
                epoch = self._store.acquire_lease(
                    self._holder, self._lease_ttl,
                    url=self.advertise_url)
            else:
                epoch = self._store.acquire_lease(self._holder,
                                                  self._lease_ttl)
        except Exception as exc:
            Log.warning("fleet: lease acquisition failed: %s: %s",
                        type(exc).__name__, exc)
            return False
        if epoch is None:
            return False
        self._store.set_fence(self._holder, int(epoch))
        if self._replay_on_acquire:
            # from-the-log-alone rebuild: nothing this process buffered
            # while standby (there should be nothing) survives
            self.buffer.reset()
            with self._lock:
                self._wins = 0
                self._consumed_rows = 0
                self._replayed_rows = 0
            self._replay()
        with self._lock:
            self._standby = False
            self._lease_epoch = int(epoch)
            self._last_renew_t = obs.monotonic()
        telemetry.count("fleet/lease_takeovers")
        Log.info("fleet: %s is now the ACTIVE trainer (lease epoch %d)",
                 self._holder, epoch)
        return True

    def wait_for_lease(self, timeout_s: float) -> bool:
        """Block until this trainer holds the lease, up to
        ``timeout_s``. With the worker running the worker's own tick
        does the acquiring; without one (``start=False``) this polls
        :meth:`try_acquire` directly."""
        deadline = obs.monotonic() + float(timeout_s)
        while True:
            with self._lock:
                if not self._standby:
                    return True
            if self._thread is None and self.try_acquire():
                return True
            remaining = deadline - obs.monotonic()
            if remaining <= 0:
                return False
            time.sleep(min(0.05, remaining))

    def _lease_tick(self) -> bool:
        """Worker-side lease duty: acquire when standby, heartbeat every
        ttl/3 when active, demote the moment a renewal fails (the fence
        epoch then blocks any publish this process still attempts).
        Returns True when active."""
        with self._lock:
            standby = self._standby
            epoch = self._lease_epoch
            last_renew = self._last_renew_t
        if standby:
            return self.try_acquire()
        if obs.monotonic() - last_renew < self._lease_ttl / 3.0:
            return True
        renewed = False
        try:
            if self.advertise_url:
                renewed = self._store.renew_lease(
                    self._holder, epoch, self._lease_ttl,
                    url=self.advertise_url)
            else:
                renewed = self._store.renew_lease(self._holder, epoch,
                                                  self._lease_ttl)
        except Exception as exc:
            Log.warning("fleet: lease renewal errored: %s: %s",
                        type(exc).__name__, exc)
        if renewed:
            with self._lock:
                self._last_renew_t = obs.monotonic()
            return True
        with self._lock:
            self._standby = True
            self._lease_epoch = 0
            self._lease_lost += 1
        telemetry.count("fleet/lease_lost")
        Log.warning("fleet: %s lost the trainer lease (epoch %d) — "
                    "demoting to standby", self._holder, epoch)
        return False

    # ------------------------------------------------------------- heartbeats
    def heartbeat_doc(self) -> Dict[str, Any]:
        """Compact node summary recorded to the store each heartbeat —
        the trainer/standby half of the ``/fleet/status`` federation
        (replicas record the watcher-side equivalent)."""
        version = 0
        if self._store is not None:
            state = getattr(self._store, "state", None)
            if state is not None:
                try:
                    version = int(state().get("last_published_version", 0))
                except Exception:
                    version = 0
        with self._lock:
            doc = {
                "node": self._holder,
                "role": ("standby" if self._standby else "active")
                if self._lease_ttl > 0 else "solo",
                "pid": os.getpid(),
                "version": version,
                "lease_epoch": self._lease_epoch,
                "trains": self._trains,
                "promotions": self._promotions,
                "rejections": self._rejections,
                "consumed_rows": self._consumed_rows,
            }
        doc["buffered_rows"] = self.buffer.rows
        return doc

    def maybe_heartbeat(self, force: bool = False) -> bool:
        """Record a heartbeat when one is due (``heartbeat_interval_s``
        elapsed; 0 disables unless ``force``). Never raises — a store
        that cannot take a heartbeat must not perturb the train loop."""
        if self._store is None or (self._hb_interval <= 0 and not force):
            return False
        record = getattr(self._store, "record_heartbeat", None)
        if record is None:
            return False
        now = obs.monotonic()
        with self._lock:
            if not force and now - self._hb_last < self._hb_interval:
                return False
            self._hb_last = now
        try:
            ok = bool(record(self.heartbeat_doc()))
        except Exception:
            with self._lock:
                self._hb_errors += 1
            telemetry.count("fleet/heartbeat_errors")
            return False
        if ok:
            with self._lock:
                self._hb_sent += 1
        return ok

    # ---------------------------------------------------------------- cycle
    def run_once(self) -> str:
        """One synchronous train cycle: drain the buffer, build a
        candidate, shadow-score it, promote or reject. Returns
        ``"promoted"``, ``"rejected"``, ``"deferred"`` (shadow win
        banked toward ``promote_patience``, no swap yet) or
        ``"skipped"`` (not enough data), or ``"standby"`` (this trainer
        does not hold the lease — only the active holder trains). Tests
        call this directly with ``start=False``."""
        with self._lock:
            if self._standby:
                return "standby"
            self._last_train_t = obs.monotonic()
        data = self.buffer.take_training()
        if data is None or len(data[1]) < self._min_rows:
            if data is not None:
                # not enough signal yet — put it back for the next cycle
                self.buffer.push(data[0], data[1])
            self._finish("skipped", None)
            return "skipped"
        X, y = data
        with tracer.span("online/train_cycle", domain="online",
                         rows=int(len(y)), mode=self._mode):
            telemetry.count("online/train_runs")
            telemetry.count("online/trained_rows", int(len(y)))
            with self._lock:
                self._trains += 1
            # snapshot of the serving model, maintained across
            # promotions/rollbacks — everything downstream is private to
            # the builder until the guarded adopt publishes the winner
            with self._lock:
                src = self._model_str
            builder = _CandidateBuilder(self._mode, src,
                                        self._train_params,
                                        self._continue_rounds, self._decay,
                                        self._shadow_decay)
            with telemetry.timed_observe("online/train_ms"), \
                    tracer.span("online/train", domain="online"):
                candidate = (self._candidate_factory(X, y)
                             if self._candidate_factory is not None
                             else builder.build(X, y))
            accept, losses = False, None
            shadow = self.buffer.shadow()
            if shadow is not None:  # no traffic to judge on => reject
                Xs, ys = shadow
                with telemetry.timed_observe("online/shadow_ms"), \
                        tracer.span("online/shadow_score", domain="online",
                                    rows=int(len(ys))):
                    cur, cand = builder.score_pair(candidate, Xs, ys)
                losses = {"current": float(cur), "candidate": float(cand),
                          "threshold": self._threshold,
                          "rows": int(len(ys))}
                accept = bool(np.isfinite(cand)
                              and cand <= self._threshold * cur + 1e-12)
            # the drained rows are consumed either way — a rejected
            # candidate's training data is gone too, so the replay
            # watermark advances on every real cycle
            with self._lock:
                self._consumed_rows += int(len(y))
                consumed = self._consumed_rows
            if accept:
                with self._lock:
                    self._wins += 1
                    wins = self._wins
                if wins < self._patience:
                    # hysteresis: a win is banked, not acted on, until
                    # the streak reaches promote_patience
                    telemetry.count("online/deferrals")
                    self._record_gate("deferred", wins, consumed, losses)
                    self._maybe_compact(wins, consumed)
                    self._finish("deferred", losses)
                    return "deferred"
                with self._lock:
                    self._wins = 0
                self._promote(candidate, builder.serialize(candidate), src)
                self._record_gate("promoted", 0, consumed, losses)
                self._maybe_compact(0, consumed)
                self._finish("promoted", losses)
                return "promoted"
            telemetry.count("online/rejections")
            with self._lock:
                self._rejections += 1
                self._wins = 0   # a loss breaks the streak
            self._record_gate("rejected", 0, consumed, losses)
            self._maybe_compact(0, consumed)
            self._finish("rejected", losses)
            return "rejected"

    def _record_gate(self, result: str, wins: int, consumed: int,
                     losses) -> None:
        if self._store is None:
            return
        try:
            self._store.append_gate(result, wins, consumed, losses)
        except Exception as exc:
            # durability is best-effort on a full/broken disk; the live
            # promotion decision already happened
            Log.warning("fleet: gate append failed: %s: %s",
                        type(exc).__name__, exc)

    def _maybe_compact(self, wins: int, consumed: int) -> None:
        """Retention: once the event log outgrows ``compact_bytes``,
        snapshot (the gate verdict just recorded made watermark+streak
        durable) and truncate. ``keep_rows`` is the shadow window's
        capacity — the retained ingest suffix provably rebuilds both
        windows bit-identically."""
        if (self._store is None or self._compact_bytes <= 0
                or not hasattr(self._store, "compact")):
            return
        try:
            if self._store.log_bytes() <= self._compact_bytes:
                return
            kw = {}
            if self._snapshot_rows > 0:
                # snapshot-bootstrap mode (only passed when on, so fake
                # stores with the narrow compact signature keep working)
                kw["snapshot_rows"] = self._snapshot_rows
            self._store.compact(watermark=consumed, wins=wins,
                                keep_rows=self.buffer.shadow_capacity,
                                keep_artifacts=self._keep_artifacts, **kw)
        except Exception as exc:
            # retention is best-effort; an uncompacted log only costs
            # disk, never correctness
            Log.warning("fleet: compaction failed: %s: %s",
                        type(exc).__name__, exc)

    # ------------------------------------------------------------ promotion
    def _promote(self, candidate, cand_str: str, prev_str: str) -> None:
        with telemetry.timed_observe("online/promote_swap_ms"), \
                tracer.span("online/promote", domain="online"):
            token = self._booster.adopt(candidate)
        with self._lock:
            # rollback token carries the displaced model's string so the
            # snapshot cache rewinds with the swap
            self._rollback = (token, prev_str)
            self._model_str = cand_str
            self._promotions += 1
            self._last_promotion_ts = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
            if self._rb_threshold > 0:
                # arm the live watch: the verdict comes from traffic
                # ingested from here on, which the shadow gate never saw
                self._watch = {"cand_str": cand_str, "prev_str": prev_str,
                               "rows": 0}
                self._watch_chunks = []
        telemetry.count("online/promotions")
        telemetry.gauge("online/model_version",
                        self._booster.inner.model_version)
        self._publish("promotion", cand_str)

    def _publish(self, event: str, model_str: str,
                 meta: Optional[Dict[str, Any]] = None) -> None:
        if self._store is None:
            return
        try:
            self._store.publish(model_str, event=event, meta=meta)
        except Exception as exc:
            # replicas simply keep serving the previous published version
            Log.warning("fleet: publish(%s) failed: %s: %s", event,
                        type(exc).__name__, exc)

    def rollback(self) -> bool:
        """Restore the model displaced by the last promotion (single
        atomic swap, like the promotion itself). Returns False when
        there is nothing to roll back to."""
        with self._lock:
            tok = self._rollback
            self._rollback = None
            self._watch = None   # the watched promotion is being undone
            self._watch_chunks = []
        if tok is None:
            return False
        snapshot, prev_str = tok
        self._booster.restore(snapshot)
        with self._lock:
            self._model_str = prev_str
            self._last_rollback_ts = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        telemetry.count("online/rollbacks")
        # a rollback distributes like any publish: replicas converge on
        # the newest version token, which is now the restored model
        self._publish("rollback", prev_str)
        return True

    # ------------------------------------------------------------ live watch
    def watch_once(self) -> Optional[bool]:
        """Evaluate the post-promotion live watch if it is armed and
        ``rollback_min_rows`` fresh labeled rows arrived since the swap:
        score promoted vs. displaced on exactly those rows and roll back
        when the promoted model's live loss exceeds
        ``rollback_threshold`` x the displaced model's.

        One verdict per promotion. Returns True (rolled back), False
        (promotion confirmed, watch disarmed) or None (nothing to do
        yet). The worker calls this every tick; tests with ``start=False``
        drive it directly."""
        with self._lock:
            watch = self._watch
            if watch is None or watch["rows"] < self._rb_min_rows:
                return None
            self._watch = None   # claim it: one evaluation, one verdict
            chunks = self._watch_chunks
            self._watch_chunks = []
        X = np.concatenate([c[0] for c in chunks], axis=0)
        y = np.concatenate([c[1] for c in chunks])
        # private rebuilds from strings: scoring never touches the live
        # serving booster
        scorer = _WatchScorer(watch["cand_str"], watch["prev_str"])
        with telemetry.timed_observe("online/watch_ms"), \
                tracer.span("online/live_watch", domain="online",
                            rows=int(len(y))):
            cand, prev = scorer.losses(X, y)
        losses = {"promoted": float(cand), "displaced": float(prev),
                  "threshold": self._rb_threshold, "rows": int(len(y))}
        regressed = bool(not np.isfinite(cand)
                         or cand > self._rb_threshold * prev + 1e-12)
        if not regressed:
            Log.info("online: live watch confirmed promotion "
                     "(promoted=%.6g displaced=%.6g)", cand, prev)
            telemetry.count("online/watch_confirms")
            self._finish("confirmed", losses)
            return False
        Log.warning("online: live loss regressed past bound "
                    "(promoted=%.6g > %.2f x displaced=%.6g) — rolling "
                    "back", cand, self._rb_threshold, prev)
        telemetry.count("online/auto_rollbacks")
        with self._lock:
            self._auto_rollbacks += 1
        self.rollback()
        self._finish("auto_rollback", losses)
        return True

    def _finish(self, result: str, losses) -> None:
        with self._lock:
            self._last_result = result
            if losses is not None:
                self._last_losses = losses

    # ----------------------------------------------------------------- state
    def state(self) -> Dict[str, Any]:
        """JSON-serializable trainer state (surfaced on /healthz)."""
        with self._lock:
            st = {
                "running": self._thread.is_alive()
                if self._thread is not None else False,
                "mode": self._mode,
                "trigger_rows": self._trigger_rows,
                "shadow_decay": self._shadow_decay,
                "trains": self._trains,
                "promotions": self._promotions,
                "rejections": self._rejections,
                "errors": self._errors,
                "last_result": self._last_result,
                "last_error": self._last_error,
                "last_losses": self._last_losses,
                "can_rollback": self._rollback is not None,
                "promote_patience": self._patience,
                "win_streak": self._wins,
                "consumed_rows": self._consumed_rows,
                "replayed_rows": self._replayed_rows,
                "auto_rollbacks": self._auto_rollbacks,
                "last_promotion_ts": self._last_promotion_ts,
                "last_rollback_ts": self._last_rollback_ts,
                "watch_armed": self._watch is not None,
                "watch_rows": self._watch["rows"]
                if self._watch is not None else 0,
                "role": ("standby" if self._standby else "active")
                if self._lease_ttl > 0 else "solo",
                "lease_epoch": self._lease_epoch,
                "lease_holder": self._holder
                if self._lease_ttl > 0 else None,
                "lease_lost": self._lease_lost,
                "heartbeats": {
                    "interval_s": self._hb_interval,
                    "sent": self._hb_sent,
                    "errors": self._hb_errors,
                },
            }
        if self._store is not None:
            st["store"] = self._store.state()
        st["buffered_rows"] = self.buffer.rows
        st["shadow_rows"] = self.buffer.shadow_rows
        st["dropped_rows"] = self.buffer.dropped_rows
        st["total_ingested_rows"] = self.buffer.total_rows
        st["model_version"] = self._booster.inner.model_version
        return st

    # -------------------------------------------------------------- shutdown
    def close(self, timeout: Optional[float] = None, *,
              release_lease: bool = True) -> None:
        """Stop the worker (the in-flight cycle finishes). Idempotent.

        ``release_lease=False`` leaves the lease to expire on its own —
        the failover bench uses it to simulate a crash (the standby must
        wait out the ttl) and the fence stays armed so this instance's
        late publishes still raise StaleLeaseError like a real zombie's."""
        with self._lock:
            self._stopped = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._lease_ttl > 0 and self._store is not None \
                and release_lease:
            with self._lock:
                epoch = self._lease_epoch
                active = not self._standby
            if active:
                try:
                    self._store.release_lease(self._holder, epoch)
                    self._store.clear_fence()
                except Exception as exc:
                    Log.warning("fleet: lease release failed: %s: %s",
                                type(exc).__name__, exc)

    def __enter__(self) -> "OnlineTrainer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
