"""Online continual-learning subsystem: train-and-serve in one process.

Three pieces close the loop over the existing serve/ stack:

- :class:`TrafficBuffer` — bounded labeled-traffic buffer + sliding
  shadow window of recent live rows;
- :class:`OnlineTrainer` — background worker that refits (or continues
  training) off the serving thread, shadow-scores the candidate against
  recent traffic and atomically promotes it into the serving booster
  (single version-token bump under ``_cache_lock``; rollback retained);
- :class:`ModelRegistry` — multi-tenant model id -> per-model
  PredictSession/MicroBatcher map behind ``/predict/<model_id>``.

    bst = lgb.train(params, train_set)
    ot = lgb.online.OnlineTrainer(bst, trigger_rows=4096)
    ot.ingest(X_live, y_live)        # from serving traffic
    # ... background worker refits, gates, promotes; serving sessions
    # over bst pick the promoted model up on their next dispatch

The CLI wires this into ``task=serve`` via ``online_train=true`` (POST
``/ingest`` feeds the buffer) and ``serve_models=id=path,...`` for
multi-tenant serving. See README "Online training".
"""
from .buffer import TrafficBuffer
from .registry import ModelRegistry, RegistryEntry
from .trainer import OnlineTrainer

__all__ = ["TrafficBuffer", "OnlineTrainer", "ModelRegistry",
           "RegistryEntry"]
