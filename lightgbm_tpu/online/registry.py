"""Multi-tenant model registry: model id -> (booster, session, batcher).

One process can serve N models behind one HTTP endpoint
(``/predict/<model_id>``). Each entry owns its own
:class:`~lightgbm_tpu.serve.session.PredictSession` (device-resident pack
behind that booster's version token — the version-keyed caches already
isolate per booster) and :class:`~lightgbm_tpu.serve.batcher.MicroBatcher`
(per-model admission control), plus optionally an
:class:`~lightgbm_tpu.online.trainer.OnlineTrainer` refreshing it from
ingested traffic.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from .. import obs
from ..obs import telemetry
from ..utils.log import LightGBMError
from .trainer import OnlineTrainer


class RegistryEntry:
    """One served model: booster + session + batcher (+ online trainer)."""

    __slots__ = ("model_id", "booster", "session", "batcher", "online",
                 "created_at")

    def __init__(self, model_id: str, booster, session, batcher,
                 online: Optional[OnlineTrainer] = None) -> None:
        self.model_id = model_id
        self.booster = booster
        self.session = session
        self.batcher = batcher
        self.online = online
        self.created_at = obs.monotonic()

    def info(self) -> Dict[str, Any]:
        """JSON-serializable per-model state (surfaced on /healthz)."""
        stats = getattr(self.batcher, "tenant_stats", None)
        return {
            "model_version": self.booster.inner.model_version,
            "buckets": list(self.session.buckets),
            "queue_rows": self.batcher.queue_rows(),
            # fake batchers in tests may predate the tenant surface
            "tenants": stats() if callable(stats) else {},
            "age_s": round(obs.monotonic() - self.created_at, 3),
            "online": self.online.state() if self.online is not None
            else None,
        }

    def close(self) -> None:
        if self.online is not None:
            self.online.close()
        self.batcher.close()


class ModelRegistry:
    """Thread-safe id -> :class:`RegistryEntry` map.

    ``get(None)`` resolves the sole entry (or the one named
    ``"default"``) so single-model callers never spell an id; with
    several models and no default, an id is required and the lookup
    raises ``KeyError`` (the HTTP layer maps it to 404).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, RegistryEntry] = {}

    # ------------------------------------------------------------- register
    def register(self, model_id: str, booster, *, buckets=None,
                 max_batch_rows: int = 8192, max_wait_ms: float = 2.0,
                 max_queue_rows: int = 0, overload: str = "shed",
                 tenant_quota_rows: int = 0, tenant_weights=None,
                 raw_score: bool = False, warmup: bool = False,
                 dispatch_mode: str = "continuous", forest=None,
                 online=None) -> RegistryEntry:
        """Build and register the serving stack for one model.

        ``online`` is either a ready :class:`OnlineTrainer` or a dict of
        its keyword arguments (a trainer is built over ``booster``).
        """
        from ..serve.batcher import MicroBatcher
        from ..serve.session import PredictSession

        model_id = str(model_id)
        if not model_id:
            raise LightGBMError("model_id must be non-empty")
        session = PredictSession(booster, buckets=buckets, forest=forest)
        if warmup:
            session.warmup()
        batcher = MicroBatcher(session, max_batch_rows=max_batch_rows,
                               max_wait_ms=max_wait_ms, raw_score=raw_score,
                               max_queue_rows=max_queue_rows,
                               overload=overload,
                               tenant_quota_rows=tenant_quota_rows,
                               tenant_weights=tenant_weights,
                               dispatch_mode=dispatch_mode)
        trainer = online
        if isinstance(online, dict):
            trainer = OnlineTrainer(booster, **online)
        entry = RegistryEntry(model_id, booster, session, batcher, trainer)
        self.add_entry(entry)
        return entry

    def add_entry(self, entry: RegistryEntry) -> RegistryEntry:
        """Register a pre-built entry (tests inject fake sessions)."""
        with self._lock:
            if entry.model_id in self._entries:
                raise LightGBMError("model id %r is already registered"
                                    % entry.model_id)
            self._entries[entry.model_id] = entry
            count = len(self._entries)
        telemetry.gauge("serve/models", count)
        return entry

    # --------------------------------------------------------------- lookup
    def get(self, model_id: Optional[str] = None) -> RegistryEntry:
        with self._lock:
            if model_id is None:
                if len(self._entries) == 1:
                    return next(iter(self._entries.values()))
                entry = self._entries.get("default")
                if entry is not None:
                    return entry
                raise KeyError(
                    "model id required (%d models registered, none named "
                    "'default')" % len(self._entries))
            entry = self._entries.get(str(model_id))
            if entry is None:
                raise KeyError("unknown model id %r (registered: %s)"
                               % (model_id, ", ".join(sorted(self._entries))
                                  or "<none>"))
            return entry

    def ids(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, model_id) -> bool:
        with self._lock:
            return str(model_id) in self._entries

    def entries(self) -> List[RegistryEntry]:
        with self._lock:
            return list(self._entries.values())

    def info(self) -> Dict[str, Any]:
        """Per-model info map (the /healthz ``models`` section)."""
        return {e.model_id: e.info() for e in self.entries()}

    # -------------------------------------------------------------- shutdown
    def close(self) -> None:
        """Close every entry (online trainers first, then batchers)."""
        for e in self.entries():
            e.close()
