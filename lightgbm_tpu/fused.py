"""Fused multi-iteration training blocks.

TPU-first restructuring of the boosting loop: the reference pays a C++
function call per phase (gbdt.cpp:369 TrainOneIter — Boosting, Bagging,
learner Train, UpdateScore); a naive port pays a *device launch* per phase,
which dominates wall-clock on a TPU behind a tunnel. Instead, when no
per-iteration host observation is needed (no valid-set eval, no
objective leaf renewal, no custom fobj), K whole boosting iterations —
gradients, in-graph bagging/GOSS sampling, tree growth, score update — run
as ONE jitted ``lax.scan``: one launch and one small device->host transfer
of the stacked split logs per K trees.

In-graph sampling reproduces the reference semantics (bagging re-drawn every
``bagging_freq`` iters, gbdt.cpp:228; GOSS top-|g·h| with amplification,
goss.hpp:103) using jax.random instead of the host RNG.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import obs_device
from .config import Config
from .learner import SerialTreeLearner, TreeLog, leaf_values_by_row
from .obs import sync, telemetry, trace_phase, track_jit
from .utils.timer import global_timer

# Process-wide cache of jitted block functions. A Booster's jitted callables
# die with the Booster, so back-to-back train() calls with identical
# config/shape fingerprints (the bench's warmup+timed pair, CV folds, the
# test suite) would re-pay trace+lower+compile (~20-30 s at 2M rows) per
# call. All data-dependent arrays are passed as jit ARGUMENTS (never closure
# constants), so a fingerprint hit is safe across Booster instances: the
# cached trace reads its array state from the call's operands.
_BLOCK_CACHE: dict = {}  # graftlint: disable=module-mutable-state -- cross-Booster jit cache; keyed by shape fingerprint
_BLOCK_CACHE_MAX = 64


def _fp_hash(x) -> str:
    import hashlib
    h = hashlib.sha1()
    if isinstance(x, np.ndarray):
        h.update(str(x.dtype).encode()); h.update(str(x.shape).encode())
        h.update(np.ascontiguousarray(x).tobytes())
    elif isinstance(x, jax.Array):
        return _fp_hash(np.asarray(x))
    elif isinstance(x, (list, tuple)):
        for v in x:
            h.update(_fp_hash(v).encode())
    elif isinstance(x, dict):
        for k in sorted(x):
            h.update(str(k).encode()); h.update(_fp_hash(x[k]).encode())
    else:
        h.update(repr(x).encode())
    return h.hexdigest()


def _config_fp(cfg: Config) -> str:
    import dataclasses
    items = []
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, (list, dict)):
            v = repr(v)
        items.append((f.name, v))
    return _fp_hash(items)


def _is_array_tree(v) -> bool:
    """True for a non-empty pytree (list/tuple/dict nesting) whose leaves
    are ALL jax.Arrays — e.g. the ranking objectives' per-bucket tables."""
    if isinstance(v, jax.Array):
        return True
    if isinstance(v, (list, tuple)):
        return bool(v) and all(_is_array_tree(x) for x in v)
    if isinstance(v, dict):
        return bool(v) and all(_is_array_tree(x) for x in v.values())
    return False


def _obj_array_state(obj) -> dict:
    """The objective's jax.Array(-pytree) attributes, passed as jit
    operands so no N-sized data embeds in the trace."""
    return {k: v for k, v in vars(obj).items() if _is_array_tree(v)}


def _obj_static_fp(obj) -> str:
    """Fingerprint of everything on the objective that is NOT passed as an
    operand (python scalars, np arrays — these embed in the trace). Array
    pytrees contribute their structure + leaf signatures only."""
    items = []
    skip = getattr(obj, "fp_skip_attrs", ())
    for k in sorted(vars(obj)):
        if k in skip:
            # host mirrors of device operands: never read by traced code,
            # and hashing 2M-row arrays per block fingerprint is waste
            continue
        v = getattr(obj, k)
        if _is_array_tree(v):
            sig = [(str(a.shape), str(a.dtype)) for a in jax.tree.leaves(v)]
            items.append((k, "arrtree", repr(jax.tree.structure(v)),
                          repr(sig)))
        else:
            items.append((k, _fp_hash(v)))
    return _fp_hash([type(obj).__name__, items])


class BlockLogs(NamedTuple):
    """Stacked per-tree split logs for one fused block: (k, T_per_iter, ...)"""
    num_splits: jax.Array
    split_leaf: jax.Array
    feature: jax.Array
    bin: jax.Array
    kind: jax.Array
    default_left: jax.Array
    gain: jax.Array
    left_sum: jax.Array
    right_sum: jax.Array
    go_left: jax.Array
    leaf_value: jax.Array


def _small(log: TreeLog, has_categorical: bool) -> BlockLogs:
    # go_left is only consumed for categorical splits (numerical routing
    # rebuilds from feature/bin/default_left); dropping the (R, B) table
    # from the per-block device->host transfer saves its payload entirely
    # on categorical-free datasets
    return BlockLogs(
        num_splits=log.num_splits, split_leaf=log.split_leaf,
        feature=log.feature, bin=log.bin, kind=log.kind,
        default_left=log.default_left, gain=log.gain,
        left_sum=log.left_sum, right_sum=log.right_sum,
        go_left=log.go_left if has_categorical else log.go_left[:0],
        leaf_value=log.leaf_value)


def _seed_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(int(seed) & 0x7FFFFFFF)


def make_sampler(config: Config, num_data: int):
    """In-graph (inbag, amplification) masks; None when sampling is off.

    The RNG streams derive from ``bagging_seed`` alone (NOT the boosting
    key), so the eager host loop and the fused device blocks draw IDENTICAL
    masks for the same config — the reference's seed contract
    (config.h bagging_seed; gbdt.cpp:228 Bagging uses its own Random).
    """
    cfg = config
    if cfg.data_sample_strategy == "goss":
        warmup = int(1.0 / max(cfg.learning_rate, 1e-12))
        top_rate, other_rate = cfg.top_rate, cfg.other_rate
        if top_rate + other_rate >= 1.0:
            return None
        base = _seed_key(cfg.bagging_seed)

        def goss(key, it, g, h):
            s = jnp.abs(g * h) if g.ndim == 1 else jnp.sum(jnp.abs(g * h), axis=1)
            top_k = max(1, int(num_data * top_rate))
            # k-th largest via top_k (O(N log k)) — same multiset element as
            # jnp.sort(s)[num_data - top_k], so `is_top` is bit-compatible
            # with the full-sort threshold (pinned in test_goss_compact.py)
            thr = jax.lax.top_k(s, top_k)[0][top_k - 1]
            is_top = s >= thr
            rest_rate = other_rate / max(1e-12, 1.0 - top_rate)
            u = jax.random.uniform(jax.random.fold_in(base, 7000 + it),
                                   (num_data,))
            sampled = (u < rest_rate) & ~is_top
            amp = (1.0 - top_rate) / max(other_rate, 1e-12)
            inbag = (is_top | sampled).astype(jnp.float32)
            ampv = jnp.where(sampled, amp, 1.0).astype(jnp.float32)
            warm = it < warmup
            ones = jnp.ones((num_data,), jnp.float32)
            return (jnp.where(warm, ones, inbag), jnp.where(warm, ones, ampv))

        return goss
    need = cfg.bagging_freq > 0 and (
        cfg.bagging_fraction < 1.0 or cfg.pos_bagging_fraction < 1.0
        or cfg.neg_bagging_fraction < 1.0)
    if not need:
        return None
    freq = max(1, cfg.bagging_freq)
    base = _seed_key(cfg.bagging_seed)

    def bagging(key, it, g, h):
        rnd = it // freq
        u = jax.random.uniform(jax.random.fold_in(base, 9000 + rnd),
                               (num_data,))
        mask = (u < cfg.bagging_fraction).astype(jnp.float32)
        return mask, jnp.ones((num_data,), jnp.float32)

    return bagging


def make_balanced_sampler(config: Config, label: jax.Array):
    cfg = config
    freq = max(1, cfg.bagging_freq)
    pos = label > 0
    base = _seed_key(cfg.bagging_seed)

    def bagging(key, it, g, h):
        rnd = it // freq
        u = jax.random.uniform(jax.random.fold_in(base, 9000 + rnd),
                               label.shape)
        mask = jnp.where(pos, u < cfg.pos_bagging_fraction,
                         u < cfg.neg_bagging_fraction).astype(jnp.float32)
        return mask, jnp.ones(label.shape, jnp.float32)

    return bagging


def make_feature_mask_fn(config: Config, num_feat: int):
    """Per-iteration by-tree column mask; shared by eager and fused paths
    (stream derives from feature_fraction_seed)."""
    cfg = config
    if cfg.feature_fraction >= 1.0:
        return None
    kk = max(1, int(np.ceil(cfg.feature_fraction * num_feat)))
    base = _seed_key(cfg.feature_fraction_seed)

    def fmask(it):
        u = jax.random.uniform(jax.random.fold_in(base, 555 + it),
                               (num_feat,))
        rank = jnp.argsort(jnp.argsort(u))
        return rank < kk

    return fmask


class FusedTrainer:
    """Builds and caches the jitted K-iteration block function for a GBDT."""

    def __init__(self, gbdt) -> None:
        self.gbdt = gbdt
        self.learner: SerialTreeLearner = gbdt.learner
        self.config: Config = gbdt.config
        cfg = self.config
        self._balanced = bool(
            cfg.data_sample_strategy != "goss"
            and (cfg.pos_bagging_fraction < 1.0
                 or cfg.neg_bagging_fraction < 1.0)
            and cfg.bagging_freq > 0 and gbdt.objective.label is not None)
        self.num_feat = gbdt.train_set.num_features
        # pipeline state: the dispatched-but-not-finalized block and the
        # device-resident cegb feature-used mask
        self._pending = None
        self._cegb_used_dev = None

    def _fingerprint(self, k: int) -> tuple:
        """Everything that shapes the traced block computation but is not a
        jit operand: the resolved config, the objective's static state, the
        learner's closed-over arrays (EFB bundle, forced splits, interaction
        constraints), and the operand shape signature."""
        g = self.gbdt
        lrn = self.learner
        bins = lrn.bins
        return (
            k, g.num_tree_per_iteration, type(lrn).__name__,
            _config_fp(g.config), _obj_static_fp(g.objective),
            str(bins.shape), str(bins.dtype), str(g.train_score.score.shape),
            lrn.num_bin_hist,
            # hp derives from config AND dataset facts (categorical columns
            # arrive via the Dataset API, not Config) — e.g.
            # has_categorical shapes the traced go_left output
            tuple(lrn.hp),
            (lrn.comm.axis, lrn.comm.mode, lrn.comm.top_k,
             lrn.comm.num_machines),
            _fp_hash(lrn.bundle), _fp_hash(lrn._forced_splits()),
            _fp_hash(lrn._constraint_sets()),
        )

    def _block_fn(self, k: int):
        fp = self._fingerprint(k)
        fn = _BLOCK_CACHE.get(fp)
        if fn is not None:
            return fn
        gbdt = self.gbdt
        learner = self.learner
        cfg = self.config
        obj = gbdt.objective
        K = gbdt.num_tree_per_iteration
        lr = float(cfg.learning_rate)
        balanced = self._balanced
        nf = self.num_feat
        fmask_fn = make_feature_mask_fn(cfg, nf)
        build = learner.make_build_fn()
        wspec = learner.work_buf_spec()
        rspec = learner.resident_spec()

        def one_iter(sampler, bins, bins_t, bins_res, meta, score, cegb_used,
                     wbuf, key, it):
            if obj.needs_iter:
                g, h = obj.get_gradients(score, it)
            else:
                g, h = obj.get_gradients(score)
            if sampler is not None:
                inbag, amp = sampler(key, it, g, h)
            else:
                inbag = amp = None
            if fmask_fn is not None:
                fmask = fmask_fn(it)
            else:
                fmask = jnp.ones((nf,), bool)
            logs = []
            for c in range(K):
                gc = g if g.ndim == 1 else g[:, c]
                hc = h if h.ndim == 1 else h[:, c]
                if inbag is not None:
                    gc, hc = gc * amp * inbag, hc * amp * inbag
                    cnt = inbag
                else:
                    cnt = jnp.ones_like(gc)
                ghc = jnp.stack([gc, hc, cnt], axis=1)
                if wspec is not None:
                    log, wbuf = build(
                        bins, ghc, meta, fmask,
                        jax.random.fold_in(key, it * 131 + c), cegb_used,
                        work_buf=wbuf, return_work=True, bins_t=bins_t,
                        bins_res=bins_res)
                else:
                    log = build(bins, ghc, meta, fmask,
                                jax.random.fold_in(key, it * 131 + c),
                                cegb_used)
                valid_r = jnp.arange(log.feature.shape[0]) < log.num_splits
                cegb_used = cegb_used.at[
                    jnp.where(valid_r, log.feature, nf)].set(True, mode="drop")
                with trace_phase("lgbtpu/score_update"):
                    vals = log.leaf_value * jnp.float32(lr)
                    upd = leaf_values_by_row(vals, log.row_leaf,
                                             vals.shape[0]) \
                        * (log.num_splits > 0)
                    if K > 1:
                        score = score.at[:, c].add(upd)
                    else:
                        score = score + upd
                logs.append(_small(log, learner.hp.has_categorical))
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *logs) if K > 1 else logs[0]
            return score, cegb_used, wbuf, stacked

        @jax.jit
        def run_block(score, cegb_used, key, it0, bins, meta, ostate):
            # Array state rides in as operands; swap it onto the objective
            # for the duration of the trace so nothing N-sized embeds in the
            # program (embedded constants made lowering + compile-cache
            # serialization scale with the dataset: ~30 s/call at 2M rows).
            saved = {a: getattr(obj, a) for a in ostate}
            for a, v in ostate.items():
                setattr(obj, a, v)
            try:
                if balanced:
                    sampler = make_balanced_sampler(cfg, obj.label)
                else:
                    sampler = make_sampler(cfg, score.shape[0])
                # one ping-pong work buffer allocated per block and carried
                # across the k trees (a fresh alloc+zero per tree costs
                # ~260 MB of HBM writes at 2M rows). The spec is layout-
                # aware: (2, Npad, W) row-major or (2, W, Npad) transposed
                # planes (learner.work_buf_spec / tpu_work_layout) — this
                # loop never looks inside the buffer
                wbuf = jnp.zeros(wspec[0], wspec[1]) \
                    if wspec is not None else jnp.zeros((), jnp.uint8)
                # transposed bins for the per-tree routing pass, computed
                # once per block (loop-invariant; ~20 ms at 2M x 28). When
                # the Pallas route kernel applies, hoist its padded
                # (F, npad/128, 128) block form so no per-tree pad/reshape
                # copy rides inside the scan body.
                bins_t = None
                if wspec is not None:
                    from .ops.route import ROUTE_BLOCK_ROWS, pltpu
                    bins_t = bins.T
                    if (pltpu is not None and not learner.hp.has_categorical
                            and jax.default_backend() in ("tpu", "axon")):
                        n_ = bins.shape[0]
                        npad = ((n_ + ROUTE_BLOCK_ROWS - 1)
                                // ROUTE_BLOCK_ROWS) * ROUTE_BLOCK_ROWS
                        if npad != n_:
                            bins_t = jnp.pad(bins_t,
                                             ((0, 0), (0, npad - n_)))
                        bins_t = bins_t.reshape(bins.shape[1],
                                                npad // 128, 128)
                # resident bin planes for tpu_resident_state: uploaded once
                # per block in ORIGINAL row order; the per-split partition
                # only permutes the slim route/ridx/g/h/c payload and the
                # histogram gathers bins through the row-index plane.
                bins_res = None
                if rspec is not None:
                    from .ops.partition import resident_bin_planes
                    bins_res = resident_bin_planes(bins, *rspec)

                def body(carry, i):
                    score, used, wbuf = carry
                    score, used, wbuf, stacked = one_iter(
                        sampler, bins, bins_t, bins_res, meta, score, used,
                        wbuf, key, it0 + i)
                    return (score, used, wbuf), stacked
                (score, used, _), stacked = jax.lax.scan(
                    body, (score, cegb_used, wbuf), jnp.arange(k))
                return (score, used), stacked
            finally:
                for a, v in saved.items():
                    setattr(obj, a, v)

        if len(_BLOCK_CACHE) >= _BLOCK_CACHE_MAX:
            _BLOCK_CACHE.clear()
        run_block = track_jit("fused/run_block", run_block)
        _BLOCK_CACHE[fp] = run_block
        return run_block

    def run(self, k: int) -> bool:
        """Run k fused iterations. Returns True when training should stop.

        Pipelined: the device block is dispatched (async) and the PREVIOUS
        block's host-side work — the blocking logs transfer and per-tree
        reconstruction (~80 ms/iter on a 1-core host) — happens while the
        new block executes on device. The returned stop signal therefore
        refers to the previous block; when it fires, the in-flight block's
        state is rolled back so the model matches the non-pipelined
        semantics exactly (training stops at the first all-constant
        iteration; reference: gbdt.cpp:379 "no more leaves"). Callers must
        invoke :meth:`flush` when the training loop ends.

        Every tree a kept block computed is appended (constant trees
        contributed zero score in-graph via the num_splits mask), so model
        and score stay consistent for rollback/continued training."""
        gbdt = self.gbdt
        with global_timer.timed("fused/block_fn"):
            fn = self._block_fn(k)
        prev = self._pending
        # iter_ only advances when a block is FINALIZED (keeps iter_ and
        # models consistent if finalization fails); schedule from iter_ plus
        # the not-yet-finalized block's length
        it0 = gbdt.iter_ + (prev[1] if prev is not None else 0)
        pre_score = gbdt.train_score.score
        pre_used = self._used_dev()
        # host-side counters only — the dispatch stays async (no sync here;
        # the real device wait is the logs transfer in _finalize)
        telemetry.count("fused/blocks_dispatched")
        telemetry.count("fused/iters_dispatched", k)
        with global_timer.timed("fused/dispatch"), \
                trace_phase("lgbtpu/fused_dispatch"):
            (score, used), logs = fn(pre_score, pre_used,
                                     gbdt._key, jnp.int32(it0),
                                     self.learner.bins, self.learner.meta,
                                     _obj_array_state(gbdt.objective))
        gbdt.train_score.score = score
        self._cegb_used_dev = used
        if self.config.obs_check_finite != "off":
            # opt-in watchdog: one fused isfinite reduction over the
            # block's output scores. The scalar fetch waits on THIS block,
            # trading the one-block pipeline overlap for catching a NaN
            # blow-up at the block it happened (grads are internal to the
            # scan; a non-finite grad surfaces in the scores it produces).
            obs_device.check_finite("scores", (score,),
                                    self.config.obs_check_finite)
        # pre_score/pre_used ride along for the rollback paths below
        self._pending = (logs, k, pre_score, pre_used)
        stopped = self._finalize(prev)
        if stopped:
            # previous block ended all-constant: drop the in-flight block
            # (its trees would all be constant too, but the reference model
            # stops at the first all-constant iteration)
            self._rollback(pre_score, pre_used)
        return stopped

    def _used_dev(self) -> jax.Array:
        dev = self._cegb_used_dev
        if dev is None:
            dev = jnp.asarray(self.gbdt._cegb_used)
        return dev

    def _rollback(self, pre_score, pre_used) -> None:
        """Drop the in-flight block and restore pre-block device state."""
        self.gbdt.train_score.score = pre_score
        self._cegb_used_dev = pre_used
        self._pending = None

    def flush(self, reason: str = "unspecified") -> bool:
        """Finalize the in-flight block (if any) and sync host-side state.
        Returns True when the finalized block ended all-constant.

        ``reason`` names which read API forced the flush (predict,
        model_to_string, train_end, ...) — counted under
        ``fused/flush/<reason>`` only when a block was actually in flight,
        so the counters show exactly which entry points break the
        pipeline's one-block overlap."""
        pending = self._pending
        self._pending = None
        if pending is not None:
            telemetry.count("fused/flush/" + reason)
        try:
            stopped = self._finalize(pending)
        except BaseException:
            # best-effort sync while an exception is already propagating —
            # only here is swallowing a secondary failure acceptable
            dev = self._cegb_used_dev
            if dev is not None:
                try:
                    # np.array, not asarray: a device buffer viewed through
                    # asarray is read-only, which breaks continued training
                    self.gbdt._cegb_used = np.array(dev)
                    self._cegb_used_dev = None
                except Exception:
                    pass
            raise
        dev = self._cegb_used_dev
        if dev is not None:
            self.gbdt._cegb_used = np.array(dev)
            self._cegb_used_dev = None
        return stopped

    def _finalize(self, pending) -> bool:
        """Append a dispatched block's trees and advance iter_. On failure
        (device error, interrupt during the transfer or the host tree loop)
        the booster rolls back to its last finalized state: score/used
        revert to the block's inputs, no partial trees are kept, and any
        in-flight successor block is dropped."""
        if pending is None:
            return False
        logs, k, pre_score, pre_used = pending
        gbdt = self.gbdt
        K = gbdt.num_tree_per_iteration
        last_iter_constant = False
        trees = []
        try:
            # Device-time attribution (ADVICE item 4): the old single
            # logs_transfer block conflated waiting for the device with
            # pulling the payload, making "transfer" a >90% catch-all in
            # the bench breakdown. Split per discipline v2: a forced
            # 1-element transfer (obs.sync — the only trusted completion
            # barrier) bounds non-overlapped DEVICE time as the host
            # experiences it; the device_get that follows is then the
            # pure host<-device payload pull. Pipelining is preserved:
            # _finalize waits on the PREVIOUS block while the freshly
            # dispatched one executes.
            with global_timer.timed("fused/device_wait"), \
                    trace_phase("lgbtpu/fused_device_wait"):
                sync(logs)
            with global_timer.timed("fused/logs_transfer"), \
                    trace_phase("lgbtpu/fused_flush"):
                host = jax.device_get(logs)
            obs_device.maybe_sample_hbm()   # block-boundary HBM watermark
            with global_timer.timed("fused/host_trees"):
                for i in range(k):
                    all_constant = True
                    for c in range(K):
                        pick = (lambda a: a[i, c] if K > 1 else a[i])
                        tree = self._host_tree(host, pick)
                        tree.apply_shrinkage(
                            float(self.config.learning_rate))
                        trees.append(tree)
                        if tree.num_leaves > 1:
                            all_constant = False
                    last_iter_constant = all_constant
        except BaseException:
            self._rollback(pre_score, pre_used)
            raise
        # atomic commit: models/iter_/version move together only on full
        # success, under the model lock so serving never packs mid-commit
        with gbdt._cache_lock:
            gbdt.models.extend(trees)
            gbdt.iter_ += k
            gbdt._bump_model_version()
        self._count_trees(trees)
        return last_iter_constant

    def _count_trees(self, trees) -> None:
        """Host-side growth/launch accounting for a finalized block. Runs
        AFTER the logs transfer (no extra sync): splits/leaves come off the
        already-fetched host trees; partition/histogram launch counts
        follow the builder's contract — one partition pass and one
        smaller-child histogram per split, plus one root histogram per
        tree on the rows layout (planes/resident fold the root histogram
        into the pack pass)."""
        splits = sum(t.num_leaves - 1 for t in trees)
        leaves = sum(t.num_leaves for t in trees)
        telemetry.count("tree/trees", len(trees))
        telemetry.count("tree/splits", splits)
        telemetry.count("tree/leaves", leaves)
        try:
            spec = self.learner.traffic_spec()
        except Exception:
            spec = None
        root_hists = 0 if (spec and spec["work_layout"] != "rows") \
            else len(trees)
        one_kernel = bool(spec and spec.get("split_kernel") == "on")
        # one-kernel split: the fused launch IS the partition launch; the
        # per-split child histogram and split-scan launches disappear
        hist_launches = root_hists if one_kernel else splits + root_hists
        scan_launches = 0 if one_kernel else splits
        telemetry.count("learner/partition_launches", splits)
        telemetry.count("learner/hist_launches", hist_launches)
        telemetry.count("learner/scan_launches", scan_launches)
        if spec:
            telemetry.gauge("traffic/work_layout", spec["work_layout"])
            telemetry.gauge("traffic/partition_bytes_per_row",
                            spec["partition_bytes_per_row"])
            telemetry.gauge("traffic/hist_bytes_per_row",
                            spec["hist_bytes_per_row"])
            telemetry.gauge("traffic/effective_rows",
                            spec.get("effective_rows", 0))
            telemetry.gauge("learner/launches_per_split",
                            spec.get("launches_per_split",
                                     3 if not one_kernel else 1))

    def _host_tree(self, host: BlockLogs, pick):
        from .tree import Tree
        ds = self.learner.dataset
        has_tbl = host.go_left.shape[-2] > 0
        return Tree.from_split_log(
            int(pick(host.num_splits)),
            pick(host.split_leaf), pick(host.feature), pick(host.bin),
            pick(host.default_left), pick(host.gain), pick(host.left_sum),
            pick(host.right_sum), pick(host.leaf_value),
            bin_mappers=ds.bin_mappers,
            real_feature_index=ds.used_feature_indices,
            go_left_table=pick(host.go_left) if has_tbl else None,
            is_categorical=pick(host.kind) > 0,
        )
