"""Training callbacks (reference: python-package/lightgbm/callback.py:51-146
print_evaluation / record_evaluation / reset_parameter / early_stopping, with
the same CallbackEnv protocol)."""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Union

from .utils.log import Log

# ``telemetry`` (the process-global obs.Telemetry registry) defaults to None
# so positional six-field constructions keep working.
CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list", "telemetry"],
    defaults=(None,))


class EarlyStopException(Exception):
    """(reference: callback.py EarlyStopException)"""

    def __init__(self, best_iteration: int, best_score) -> None:
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _fmt_eval(res) -> str:
    name, metric, value, _ = res[:4]
    return "%s's %s: %g" % (name, metric, value)


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """(reference: callback.py:51 print_evaluation)"""

    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(_fmt_eval(x) for x in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)

    _callback.order = 10
    return _callback


print_evaluation = log_evaluation


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    """(reference: callback.py:74)"""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        for name, metric, _, _ in env.evaluation_result_list or []:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for name, metric, value, _ in env.evaluation_result_list or []:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, []).append(value)

    _callback.order = 20
    return _callback


def reset_parameter(**kwargs: Union[list, Callable]) -> Callable:
    """Schedule parameters by iteration, e.g. learning_rate=list|fn
    (reference: callback.py:105)."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError("Length of list %r should equal num_boost_round"
                                     % key)
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
        if new_params:
            env.model.reset_parameter(new_params)

    _callback.before_iteration = True
    _callback.order = 10
    return _callback


class _MetricState:
    """Best-so-far tracker for one (dataset, metric) evaluation stream."""

    __slots__ = ("best_value", "best_round", "best_results", "sign", "tol")

    def __init__(self, greater_is_better: bool, min_delta: float) -> None:
        # store scores as "higher is better" internally so one comparison
        # serves both orientations
        self.sign = 1.0 if greater_is_better else -1.0
        self.tol = abs(min_delta)
        self.best_value = float("-inf")
        self.best_round = 0
        self.best_results = None

    def update(self, value: float, round_idx: int, results) -> None:
        oriented = self.sign * value
        if oriented > self.best_value + self.tol or self.best_results is None:
            self.best_value = oriented
            self.best_round = round_idx
            self.best_results = results

    def rounds_since_best(self, round_idx: int) -> int:
        return round_idx - self.best_round


class _EarlyStopper:
    """Stop when no tracked validation metric improved for
    ``stopping_rounds`` consecutive rounds (reference behavior:
    python-package/lightgbm/callback.py early_stopping; implementation is
    original)."""

    order = 30
    before_iteration = False

    def __init__(self, stopping_rounds: int, first_metric_only: bool,
                 verbose: bool, min_delta: float) -> None:
        if stopping_rounds <= 0:
            raise ValueError("stopping_rounds must be positive")
        self.patience = int(stopping_rounds)
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.min_delta = min_delta
        self.states: Dict[tuple, _MetricState] = {}
        self.active = None  # None = not yet initialized

    def _report(self, prefix: str, state: _MetricState) -> None:
        if self.verbose:
            detail = "\t".join(_fmt_eval(x) for x in state.best_results)
            Log.info("%s best iteration is: [%d]\t%s",
                     prefix, state.best_round + 1, detail)

    def __call__(self, env: CallbackEnv) -> None:
        results = env.evaluation_result_list
        if self.active is None:
            self.active = bool(results)
            if not self.active:
                Log.warning("Early stopping requires at least one validation set")
            elif self.verbose:
                Log.info("Training until validation scores don't improve "
                         "for %d rounds", self.patience)
        if not self.active:
            return
        tracked_metric = results[0][1]
        stop_with = None
        for name, metric, value, greater_is_better in results:
            key = (name, metric)
            state = self.states.get(key)
            if state is None:
                state = self.states[key] = _MetricState(greater_is_better,
                                                        self.min_delta)
            state.update(value, env.iteration, results)
            if name == "training":
                continue  # never stop on the training metric
            if self.first_metric_only and metric != tracked_metric:
                continue
            if stop_with is None and \
                    state.rounds_since_best(env.iteration) >= self.patience:
                stop_with = state
        last_round = env.iteration == env.end_iteration - 1
        if stop_with is None and last_round:
            for (name, _), state in self.states.items():
                if name != "training":
                    self._report("Did not meet early stopping.", state)
                    raise EarlyStopException(state.best_round, state.best_results)
        if stop_with is not None:
            self._report("Early stopping,", stop_with)
            raise EarlyStopException(stop_with.best_round, stop_with.best_results)


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    """Early-stopping callback factory (same surface as the reference
    python package's ``early_stopping``)."""
    return _EarlyStopper(stopping_rounds, first_metric_only, verbose, min_delta)
