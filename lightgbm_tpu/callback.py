"""Training callbacks (reference: python-package/lightgbm/callback.py:51-146
print_evaluation / record_evaluation / reset_parameter / early_stopping, with
the same CallbackEnv protocol)."""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Union

from .utils.log import Log

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    """(reference: callback.py EarlyStopException)"""

    def __init__(self, best_iteration: int, best_score) -> None:
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _fmt_eval(res) -> str:
    name, metric, value, _ = res[:4]
    return "%s's %s: %g" % (name, metric, value)


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """(reference: callback.py:51 print_evaluation)"""

    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(_fmt_eval(x) for x in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)

    _callback.order = 10
    return _callback


print_evaluation = log_evaluation


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    """(reference: callback.py:74)"""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        for name, metric, _, _ in env.evaluation_result_list or []:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for name, metric, value, _ in env.evaluation_result_list or []:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, []).append(value)

    _callback.order = 20
    return _callback


def reset_parameter(**kwargs: Union[list, Callable]) -> Callable:
    """Schedule parameters by iteration, e.g. learning_rate=list|fn
    (reference: callback.py:105)."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError("Length of list %r should equal num_boost_round"
                                     % key)
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
        if new_params:
            env.model.reset_parameter(new_params)

    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    """(reference: callback.py:146)"""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            Log.warning("Early stopping requires at least one validation set")
            return
        if verbose:
            Log.info("Training until validation scores don't improve for %d rounds",
                     stopping_rounds)
        first_metric[0] = env.evaluation_result_list[0][1]
        for _, _, _, greater_is_better in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if greater_is_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y + min_delta)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y - min_delta)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, (name, metric, value, _) in enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](value, best_score[i]):
                best_score[i] = value
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and first_metric[0] != metric:
                continue
            if name == "training":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info("Early stopping, best iteration is: [%d]\t%s",
                             best_iter[i] + 1,
                             "\t".join(_fmt_eval(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    Log.info("Did not meet early stopping. Best iteration is: [%d]\t%s",
                             best_iter[i] + 1,
                             "\t".join(_fmt_eval(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])

    _callback.order = 30
    return _callback
