"""Boosting drivers: GBDT / DART / GOSS sampling / RF.

TPU-native equivalent of the reference boosting layer (reference:
src/boosting/gbdt.cpp GBDT::Train/TrainOneIter, goss.hpp, dart.hpp, rf.hpp,
score_updater.hpp). The training loop stays on host (it is O(iterations),
not O(rows)); all O(rows) work — gradients, histograms, score updates,
prediction routing — is jitted device code. Scores are float32 device arrays
(the reference keeps double; the f32 choice follows its GPU precedent).
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import BinnedDataset
from .learner import (SerialTreeLearner, TreeLog, assign_leaves,
                      leaf_values_by_row)
from .metric import Metric, create_metrics
from .obs import track_jit
from .objective import ObjectiveFunction, create_objective
from .tree import Tree
from .utils.log import Log


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("class_id",), donate_argnums=(0,))
def _score_add(score, lv, leaf_assign, scale, class_id):
    """One fused launch per tree contribution (kept jitted: the eager form
    retraced per op and dominated DART/rollback wall-clock)."""
    from .obs import trace_phase
    with trace_phase("lgbtpu/score_update"):
        vals = leaf_values_by_row(lv, leaf_assign, lv.shape[0]) * scale
        if score.ndim > 1:
            return score.at[:, class_id].add(vals)
        return score + vals


_score_add = track_jit("boosting/score_add", _score_add)
# host-facing tracked alias: the learner's own (traced) assign_leaves calls
# stay on the raw jit, so only eager-path dispatches count here
assign_leaves = track_jit("learner/assign_leaves", assign_leaves)


class ScoreTracker:
    """Running raw scores for one dataset (reference: score_updater.hpp:21)."""

    def __init__(self, num_data: int, num_class: int, init: np.ndarray) -> None:
        shape = (num_data, num_class) if num_class > 1 else (num_data,)
        s = np.zeros(shape, dtype=np.float32)
        s += init if num_class > 1 else init[0]
        self.score = jnp.asarray(s)

    def add(self, leaf_values: np.ndarray, leaf_assign: jax.Array, class_id: int,
            num_class: int, scale: float = 1.0) -> None:
        lv = jnp.asarray(leaf_values, jnp.float32)
        self.score = _score_add(self.score, lv, leaf_assign,
                                jnp.float32(scale), int(class_id))

    def np(self) -> np.ndarray:
        return np.asarray(self.score)


class GBDT:
    """Gradient Boosting (reference: src/boosting/gbdt.cpp:264 Train,
    :369 TrainOneIter)."""

    name = "gbdt"

    def __init__(self, config: Config, train_set: Optional[BinnedDataset],
                 comm_axis: Optional[str] = None) -> None:
        self.config = config
        self.train_set = train_set
        self.models: List[Tree] = []
        self.iter_ = 0
        self.num_class = max(1, int(config.num_class))
        self.objective: Optional[ObjectiveFunction] = None
        self.metrics: List[Metric] = []
        self.init_scores = np.zeros(self.num_class, dtype=np.float64)
        self.valid_sets: List[Tuple[str, BinnedDataset, ScoreTracker]] = []
        self.learner: Optional[SerialTreeLearner] = None
        self.train_score: Optional[ScoreTracker] = None
        self._rng = np.random.RandomState(
            config.seed if config.seed is not None else config.data_random_seed)
        self._key = jax.random.PRNGKey(
            config.seed if config.seed is not None else 0)
        self._inbag: Optional[jax.Array] = None  # (N,) f32 0/1
        self._grad_fn = None
        self.best_iteration = -1
        self.comm_axis = comm_axis
        # monotonic token bumped whenever self.models changes content —
        # train/rollback/score-rebuild/fused-commit. Device-resident
        # prediction packs key on it (an (len, id(tree)) key is unsafe:
        # rollback + retrain can reproduce both with different trees)
        self._model_version = 0
        # guards models mutations, the version token and the serving
        # caches (_pack_cache/_serve_sessions/_tree_log_cache): a
        # PredictSession worker thread must never pack a half-committed
        # model. Re-entrant because _rebuild_scores bumps the version
        # from inside locked sections.
        self._cache_lock = threading.RLock()
        if train_set is not None:
            self._setup(train_set)

    # ------------------------------------------------------------------ setup
    def _setup(self, train_set: BinnedDataset) -> None:
        cfg = self.config
        # device-cost capture is process-global (obs_device mirrors the
        # trace_spans configure contract: last writer wins)
        from . import obs_device
        obs_device.configure(cost_enabled=cfg.obs_device_cost)
        self.objective = create_objective(cfg)
        self.objective.init(train_set.metadata)
        self.num_tree_per_iteration = self.objective.num_model_per_iteration
        self.metrics = create_metrics(cfg, self.objective.name)
        from .parallel.mesh import create_tree_learner, make_mesh
        mesh = None
        if cfg.tree_learner != "serial":
            import jax as _jax
            if len(_jax.devices()) > 1:
                mesh = make_mesh()
        self.learner = create_tree_learner(cfg, train_set, mesh)
        n = train_set.num_data
        # boost_from_average (reference: gbdt.cpp:333; distributed mean is a
        # psum at objective level — labels are row-sharded the same way)
        if cfg.boost_from_average and self.objective.name != "none" \
                and train_set.metadata.label is not None:
            for k in range(self.num_tree_per_iteration):
                self.init_scores[k] = self.objective.boost_from_score(k)
        if train_set.metadata.init_score is not None:
            base = train_set.metadata.init_score.reshape(
                n, -1) if self.num_class > 1 else train_set.metadata.init_score.ravel()
        else:
            base = None
        self.train_score = ScoreTracker(
            n, self.num_tree_per_iteration, self.init_scores)
        if base is not None:
            self.train_score.score = self.train_score.score + jnp.asarray(
                base, jnp.float32)
        self._inbag = jnp.ones((n,), jnp.float32)
        self._cegb_used = np.zeros(train_set.num_features, dtype=bool)
        self._setup_grad_fn()

    def _setup_grad_fn(self) -> None:
        obj = self.objective

        @jax.jit
        def grads(score, it):
            if obj.needs_iter:
                return obj.get_gradients(score, it)
            return obj.get_gradients(score)

        self._grad_fn = track_jit("boosting/grads", grads)

    def add_valid(self, name: str, valid_set: BinnedDataset) -> None:
        vs = ScoreTracker(valid_set.num_data, self.num_tree_per_iteration,
                          self.init_scores)
        if valid_set.metadata.init_score is not None:
            base = valid_set.metadata.init_score
            base = base.reshape(valid_set.num_data, -1) if self.num_class > 1 \
                else base.ravel()
            vs.score = vs.score + jnp.asarray(base, jnp.float32)
        # replay already-trained trees (continued training)
        if self.models:
            bins = jnp.asarray(valid_set.binned)
            Log.debug("Replaying %d trees onto valid set %s", len(self.models), name)
            for i, tree in enumerate(self.models):
                vals, leaf = self._route_tree_device(tree, valid_set)
                vs.add(vals, leaf, i % self.num_tree_per_iteration,
                       self.num_tree_per_iteration)
        self.valid_sets.append((name, valid_set, vs))

    def _route_tree_device(self, tree: Tree, ds: BinnedDataset):
        """Route a dataset's binned rows through a host Tree on device.

        Converts the tree into leaf-slot split order (bin-space thresholds)
        and reuses the learner's arithmetic router — replaces the round-1
        per-node Python walk that made DART/rollback quadratic (reference
        analogs: score_updater.hpp, dart.hpp score replay). Returns
        (slot-ordered leaf values (L,), per-row slots (N,) device array).
        """
        from .ops.predict import tree_to_bin_log

        # logs are cached per (tree state, dataset): DART re-drops the same
        # trees every iteration and each conversion costs host work plus
        # ~a dozen host->device uploads
        # content key (not id()): a GC'd tree's address can be reused by a
        # new tree with byte-identical leaf values after rollback
        key = (tree.num_leaves, tree.split_feature.tobytes(),
               tree.threshold.tobytes(), tree.decision_type.tobytes(),
               tree.leaf_value.tobytes(), id(ds))
        with self._cache_lock:
            cache = getattr(self, "_tree_log_cache", None)
            if cache is None:
                cache = self._tree_log_cache = {}
            log = cache.get(key)
        if log is None:
            # convert outside the lock (host work + uploads); a racing
            # duplicate conversion is harmless, a held lock is not
            log = tree_to_bin_log(tree, ds)
            with self._cache_lock:
                if len(cache) > 4096:
                    cache.clear()
                cache[key] = log
        if ds is self.train_set and self.learner is not None:
            bins = self.learner.bins
            bundle = self.learner.bundle
            hc = self.learner.hp.has_categorical
        else:
            bins = self._valid_bins(ds)
            bundle = None
            if ds.has_bundles:
                bundle = {k: jnp.asarray(v)
                          for k, v in ds.bundle_maps().items()}
            from .ops.binning import BIN_CATEGORICAL
            hc = any(m.bin_type == BIN_CATEGORICAL for m in ds.bin_mappers)
        leaf = assign_leaves(bins, log, has_categorical=hc, bundle=bundle)
        if leaf.shape[0] != ds.num_data:
            # mesh learners pad rows to a multiple of the device count; the
            # score buffers are unpadded (num_data) — truncate before use
            leaf = leaf[:ds.num_data]
        return np.asarray(log.leaf_value), leaf

    # --------------------------------------------------------------- sampling
    def _bagging(self, it: int, grad: jax.Array, hess: jax.Array) -> None:
        """Refresh the in-bag mask (reference: gbdt.cpp:228 Bagging,
        goss.hpp:103 for data_sample_strategy=goss).

        Uses the SAME seed-derived samplers as the fused device blocks
        (fused.make_sampler), so a given config trains the identical model
        through either path."""
        cfg = self.config
        if not hasattr(self, "_sampler_fn"):
            from .fused import make_balanced_sampler, make_sampler
            lab = self.objective.label if self.objective is not None else None
            if lab is None and self.train_set is not None \
                    and self.train_set.metadata.label is not None:
                # custom objectives (objective=none) still bag by label
                lab = self.train_set.metadata.device_label()
            # GOSS takes precedence over any bagging params (the reference's
            # data_sample_strategy switch, gbdt.cpp:228)
            if cfg.data_sample_strategy != "goss" \
                    and (cfg.pos_bagging_fraction < 1.0
                         or cfg.neg_bagging_fraction < 1.0) \
                    and cfg.bagging_freq > 0 and lab is not None:
                self._sampler_fn = make_balanced_sampler(cfg, lab)
            else:
                self._sampler_fn = make_sampler(cfg,
                                                self.train_set.num_data)
        if self._sampler_fn is None:
            self._amp = None
            return
        g = grad if grad.ndim == 1 else jnp.sum(jnp.abs(grad), axis=1)
        h = hess if hess.ndim == 1 else jnp.sum(jnp.abs(hess), axis=1)
        inbag, amp = self._sampler_fn(None, it, g, h)
        self._inbag = inbag
        self._amp = amp if cfg.data_sample_strategy == "goss" else None

    def _tree_channels(self, grad: jax.Array, hess: jax.Array, k: int) -> jax.Array:
        g = grad if grad.ndim == 1 else grad[:, k]
        h = hess if hess.ndim == 1 else hess[:, k]
        if getattr(self, "_amp", None) is not None:
            g, h = g * self._amp, h * self._amp
        m = self._inbag
        return jnp.stack([g * m, h * m, m], axis=1)

    def _feature_mask(self, it: int) -> jax.Array:
        cfg = self.config
        nf = self.train_set.num_features
        if not hasattr(self, "_fmask_fn"):
            from .fused import make_feature_mask_fn
            self._fmask_fn = make_feature_mask_fn(cfg, nf)
        if self._fmask_fn is None:
            return jnp.ones((nf,), bool)
        return self._fmask_fn(it)

    # --------------------------------------------------------------- training
    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (reference: gbdt.cpp:369 TrainOneIter).
        Returns True when no tree could be grown (all-stop signal)."""
        # while a fused block is in flight, score already includes it but
        # models/iter_ lag; entry points that read or extend them must
        # finalize first so external callers never observe divergent state
        self.finish_fused("train_one_iter")
        it = self.iter_
        if grad is None:
            g, h = self._grad_fn(self.train_score.score, jnp.int32(it))
        else:
            g = jnp.asarray(grad, jnp.float32)
            h = jnp.asarray(hess, jnp.float32)
            if self.num_class > 1:
                g = g.reshape(self.train_set.num_data, self.num_class)
                h = h.reshape(self.train_set.num_data, self.num_class)
        if self.config.obs_check_finite != "off":
            # opt-in watchdog (eager path): one fused isfinite reduction
            # over this iteration's gradients — a custom fobj or an
            # exploding objective surfaces here, at the iteration it
            # happened. Gated BEFORE any array op: off builds nothing.
            from . import obs_device
            obs_device.check_finite("grads", (g, h),
                                    self.config.obs_check_finite)
        self._bagging(it, g, h)
        self._last_grad, self._last_hess = g, h
        fmask = self._feature_mask(it)
        any_nonconstant = False
        for k in range(self.num_tree_per_iteration):
            ghc = self._tree_channels(g, h, k)
            self._last_ghc = ghc
            key = jax.random.fold_in(self._key, it * 131 + k)
            log = self.learner.train(ghc, fmask, key,
                                     jnp.asarray(self._cegb_used))
            tree = self._finalize_tree(log, k)
            with self._cache_lock:
                self.models.append(tree)
            self._note_used_features(tree)
            # eager-path growth counters (fused blocks count in _count_trees)
            from .obs import telemetry
            splits = tree.num_leaves - 1
            telemetry.count("tree/trees")
            telemetry.count("tree/splits", splits)
            telemetry.count("tree/leaves", tree.num_leaves)
            # launch accounting: one partition pass + one smaller-child
            # histogram per split; rows layout adds a root histogram per
            # tree (planes/resident fold the root into the pack)
            try:
                spec = self.learner.traffic_spec()
            except Exception:
                spec = None
            root_hists = 0 if (spec and spec["work_layout"] != "rows") else 1
            one_kernel = bool(spec and spec.get("split_kernel") == "on")
            # one-kernel split: the fused launch IS the partition launch;
            # per-split histogram and split-scan launches disappear
            telemetry.count("learner/partition_launches", splits)
            telemetry.count("learner/hist_launches",
                            root_hists if one_kernel else splits + root_hists)
            telemetry.count("learner/scan_launches",
                            0 if one_kernel else splits)
            if spec:
                telemetry.gauge("traffic/work_layout", spec["work_layout"])
                telemetry.gauge("traffic/partition_bytes_per_row",
                                spec["partition_bytes_per_row"])
                telemetry.gauge("traffic/hist_bytes_per_row",
                                spec["hist_bytes_per_row"])
                telemetry.gauge("traffic/effective_rows",
                                spec.get("effective_rows", 0))
                telemetry.gauge("learner/launches_per_split",
                                spec.get("launches_per_split", 3))
            if tree.num_leaves > 1:
                any_nonconstant = True
        if self.config.obs_check_finite != "off":
            from . import obs_device
            obs_device.check_finite("scores", (self.train_score.score,),
                                    self.config.obs_check_finite)
        with self._cache_lock:
            self.iter_ += 1
            self._bump_model_version()
        return not any_nonconstant

    def _note_used_features(self, tree: Tree) -> None:
        """Track model-level feature usage for CEGB coupled penalties
        (reference: cost_effective_gradient_boosting.hpp
        is_feature_used_in_split_)."""
        if tree.num_leaves > 1 and self.train_set is not None:
            for f in tree.split_feature[:tree.num_internal]:
                inner = self.train_set.inner_feature_index(int(f))
                if inner >= 0:
                    self._cegb_used[inner] = True

    def _shrinkage_rate(self, log: TreeLog) -> float:
        return float(self.config.learning_rate)

    def _fit_linear_tree(self, tree: Tree, log: TreeLog, grad, hess,
                         class_id: int, rate: float) -> None:
        """Fit ridge linear models in the leaves (reference:
        LinearTreeLearner::CalculateLinear, linear_tree_learner.cpp:7):
        solve -(Z^T H Z + lambda I') beta = Z^T g per leaf over the leaf's
        branch numerical features; rows with NaN in those features are
        excluded; under-determined leaves keep the plain output. The first
        iteration only copies constants (the reference skips the fit).

        Under ``linear_device`` the solve runs batched on device
        (lightgbm_tpu/linear/fit.py: all leaves' Gram matrices at once);
        this host loop stays as the parity oracle."""
        from .ops.binning import BIN_CATEGORICAL

        ds = self.train_set
        tree.is_linear = True
        # leaf_value is already shrunk; solved coefficients get the same
        # shrinkage below (reference applies Tree::Shrinkage to both)
        tree.leaf_const = tree.leaf_value.copy()
        if len(self.models) <= self.num_tree_per_iteration - 1 \
                or tree.num_leaves <= 1 or ds.raw_numeric is None:
            return
        lam = float(self.config.linear_lambda)
        if self._linear_fit_on_device():
            from .linear import fit_linear_leaves
            fit_linear_leaves(tree, ds, log.row_leaf, self._last_ghc,
                              lam=lam, rate=rate,
                              num_leaves_cap=int(self.config.num_leaves))
            return
        leaf = np.asarray(log.row_leaf)
        # use the bagged/amplified channels the tree was grown on (reference
        # fits over the bagged partition only; out-of-bag rows carry h=0
        # here, excluding them from the normal equations)
        ghc = np.asarray(self._last_ghc, np.float64)
        gk, hk = ghc[:, 0], ghc[:, 1]
        del grad, hess
        X = ds.raw_numeric
        for l in range(tree.num_leaves):
            feats = [int(f) for f in tree.branch_features(l)
                     if ds.inner_feature_index(int(f)) >= 0
                     and ds.bin_mappers[ds.inner_feature_index(int(f))]
                     .bin_type != BIN_CATEGORICAL]
            rows = np.flatnonzero(leaf == l)
            if not feats or len(rows) < len(feats) + 1:
                continue
            Z = X[np.ix_(rows, feats)].astype(np.float64)
            ok = ~np.isnan(Z).any(axis=1)
            if int(ok.sum()) < len(feats) + 1:
                continue
            Zk = np.concatenate([Z[ok], np.ones((int(ok.sum()), 1))], axis=1)
            hr = hk[rows][ok]
            A = Zk.T @ (Zk * hr[:, None])
            A[np.arange(len(feats)), np.arange(len(feats))] += lam
            b = Zk.T @ gk[rows][ok]
            try:
                beta = -np.linalg.solve(A, b)
            except np.linalg.LinAlgError:
                continue
            keep = np.abs(beta[:-1]) > 1e-35
            tree.leaf_features[l] = np.asarray(feats, np.int64)[keep]
            tree.leaf_coeff[l] = beta[:-1][keep] * rate
            tree.leaf_const[l] = float(beta[-1]) * rate

    def _linear_fit_on_device(self) -> bool:
        """Resolve ``linear_device``: off -> host oracle, on -> batched
        device solve, auto -> device only when a TPU backend is up (the
        host loop beats a CPU-jax round trip at small leaf counts)."""
        mode = self.config.linear_device
        if mode == "off":
            return False
        if mode == "on":
            return True
        return jax.default_backend() == "tpu"

    def _linear_score_updates(self, tree: Tree, log: TreeLog,
                              class_id: int) -> None:
        """Score updates for linear leaves need raw feature values, so they
        run on host (reference: score updates via Tree::AddPredictionToScore
        with PredictionFunLinear, tree.cpp:246)."""
        leaf = np.asarray(log.row_leaf)
        vals = tree.linear_predict(self.train_set.raw_numeric.astype(np.float64),
                                   leaf)
        self.train_score.score = self.train_score.score + (
            jnp.asarray(vals, jnp.float32) if self.num_tree_per_iteration == 1
            else jnp.zeros_like(self.train_score.score)
            .at[:, class_id].set(jnp.asarray(vals, jnp.float32)))
        for _, vset, vscore in self.valid_sets:
            slot_vals, vleaf = self._route_tree_device(tree, vset)
            if vset.raw_numeric is None:
                # no raw features (e.g. binary-cache valid set): fall back to
                # the plain leaf outputs so metrics stay meaningful
                Log.warning("valid set lacks raw features for linear trees; "
                            "using plain leaf outputs for its scores")
                vscore.add(slot_vals, vleaf, class_id,
                           self.num_tree_per_iteration)
                continue
            # the device router returns to_split_arrays SLOTS (BFS order);
            # linear_predict keys coefficients by LEAF id — map through
            # leaf_of_slot (they only coincide when BFS == creation order)
            leaf_of_slot = tree.to_split_arrays()["leaf_of_slot"]
            vvals = tree.linear_predict(vset.raw_numeric.astype(np.float64),
                                        leaf_of_slot[np.asarray(vleaf)])
            vscore.score = vscore.score + (
                jnp.asarray(vvals, jnp.float32)
                if self.num_tree_per_iteration == 1
                else jnp.zeros_like(vscore.score)
                .at[:, class_id].set(jnp.asarray(vvals, jnp.float32)))

    def _finalize_tree(self, log: TreeLog, class_id: int) -> Tree:
        rate = self._shrinkage_rate(log)
        if self.objective.need_renew:
            # objective-specific leaf renewal needs host stats (reference:
            # serial_tree_learner.cpp:684 RenewTreeOutput) — slow path
            tree = self.learner.log_to_tree(log)
            if tree.num_leaves > 1:
                assign = np.asarray(log.row_leaf)
                score_before = self.train_score.np()
                renewed = self.objective.renew_leaf_values(
                    assign, tree.num_leaves, score_before)
                if renewed is not None:
                    tree.leaf_value = renewed.astype(np.float64)
            tree.apply_shrinkage(rate)
            leaf_vals_dev = jnp.asarray(tree.leaf_value, jnp.float32)
        else:
            # fast path: score updates run fully on device from the log;
            # host Tree construction is a single batched transfer after
            leaf_vals_dev = log.leaf_value * jnp.float32(rate)
            tree = self.learner.log_to_tree(log)
            tree.apply_shrinkage(rate)
        if self.config.linear_tree and not self.objective.need_renew:
            self._fit_linear_tree(tree, log, self._last_grad, self._last_hess,
                                  class_id, rate)
            if tree.num_leaves > 1:
                self._linear_score_updates(tree, log, class_id)
            return tree
        # score updates: train via the partition the learner already holds
        # (reference: score_updater.hpp:88), valid via device routing.
        # Constant (1-leaf) trees contribute nothing (reference:
        # gbdt.cpp TrainOneIter skips UpdateScore when no split was found).
        if tree.num_leaves > 1:
            self.train_score.add(leaf_vals_dev, log.row_leaf, class_id,
                                 self.num_tree_per_iteration)
            for _, vset, vscore in self.valid_sets:
                vbins = self._valid_bins(vset)
                vleaf = assign_leaves(
                    vbins, log,
                    has_categorical=self.learner.hp.has_categorical,
                    bundle=self.learner.bundle)
                vscore.add(leaf_vals_dev, vleaf, class_id,
                           self.num_tree_per_iteration)
        return tree

    def _valid_bins(self, vset: BinnedDataset) -> jax.Array:
        if not hasattr(vset, "_device_bins"):
            vset._device_bins = jnp.asarray(vset.binned)
        return vset._device_bins

    # ---------------------------------------------------------- fused blocks
    def supports_fused(self) -> bool:
        """True when K iterations can run as one device launch (no per-iter
        host observation needed): plain GBDT, built-in objective without
        leaf renewal, no valid sets, single-device learner."""
        from .parallel.mesh import _MeshTreeLearner
        return (type(self) is GBDT
                and not self.config.linear_tree
                and self.objective is not None
                and self.objective.name != "none"
                and not self.objective.need_renew
                and not self.valid_sets
                and self.train_set is not None
                and not isinstance(self.learner, _MeshTreeLearner))

    def train_block(self, k: int) -> bool:
        """Train k iterations fused in one launch (see fused.py)."""
        if getattr(self, "_fused", None) is None:
            from .fused import FusedTrainer
            self._fused = FusedTrainer(self)
        return self._fused.run(k)

    def finish_fused(self, reason: str = "unspecified") -> bool:
        """Finalize any in-flight fused block (host trees + cegb state).
        ``reason`` names the calling read API for the
        ``fused/flush/<reason>`` telemetry counters."""
        if getattr(self, "_fused", None) is None:
            return False
        return self._fused.flush(reason)

    def rollback_one_iter(self) -> None:
        """(reference: gbdt.cpp:454 RollbackOneIter)"""
        self.finish_fused("rollback_one_iter")
        if self.iter_ <= 0:
            return
        with self._cache_lock:
            for _ in range(self.num_tree_per_iteration):
                tree = self.models.pop()
                del tree
            self.iter_ -= 1
            self._bump_model_version()
        # scores must be rebuilt; mark dirty and recompute lazily
        self._rebuild_scores()

    def _rebuild_scores(self) -> None:
        # callers reach here after mutating self.models (rollback, continued
        # training preload) — invalidate any device-resident predict packs
        self._bump_model_version()
        K = self.num_tree_per_iteration

        def fresh_tracker(ds: BinnedDataset) -> ScoreTracker:
            ts = ScoreTracker(ds.num_data, K, self.init_scores)
            if ds.metadata.init_score is not None:
                base = ds.metadata.init_score
                base = base.reshape(ds.num_data, -1) if self.num_class > 1 \
                    else base.ravel()
                ts.score = ts.score + jnp.asarray(base, jnp.float32)
            return ts

        ts = fresh_tracker(self.train_set)
        for i, tree in enumerate(self.models):
            vals, leaf = self._route_tree_device(tree, self.train_set)
            ts.add(vals, leaf, i % K, K)
        self.train_score = ts
        rebuilt = []
        for name, vset, _ in self.valid_sets:
            vs = fresh_tracker(vset)
            for i, tree in enumerate(self.models):
                vals, leaf = self._route_tree_device(tree, vset)
                vs.add(vals, leaf, i % K, K)
            rebuilt.append((name, vset, vs))
        self.valid_sets = rebuilt

    # ------------------------------------------------------------------- eval
    def eval_set(self, name: str, ds: BinnedDataset, tracker: ScoreTracker,
                 feval=None) -> List[Tuple[str, str, float, bool]]:
        out = []
        score = tracker.score
        conv = np.asarray(self.objective.convert_output(score))
        md = ds.metadata
        for m in self.metrics:
            for mname, val in m.eval(conv, md.label, md.weight, md.query_boundaries):
                out.append((name, mname, float(val), m.greater_is_better))
        if feval is not None:
            res = feval(np.asarray(score), ds)
            if res:
                if isinstance(res[0], (list, tuple)):
                    for mname, val, gib in res:
                        out.append((name, mname, float(val), bool(gib)))
                else:
                    mname, val, gib = res
                    out.append((name, mname, float(val), bool(gib)))
        return out

    def eval_train(self, feval=None):
        return self.eval_set("training", self.train_set, self.train_score, feval)

    def eval_valid(self, feval=None):
        out = []
        for name, ds, tracker in self.valid_sets:
            out.extend(self.eval_set(name, ds, tracker, feval))
        return out

    # ---------------------------------------------------------------- predict
    DEVICE_PREDICT_MIN_ROWS = 512

    @property
    def model_version(self) -> int:
        """Monotonic model-content token (see __init__)."""
        return self._model_version

    def _bump_model_version(self) -> None:
        with self._cache_lock:
            self._model_version += 1

    # ------------------------------------------------------- hot swap (online)
    def adopt(self, other: "GBDT") -> tuple:
        """Atomically swap this booster's served model for ``other``'s.

        The online promotion hook: a candidate trained off the serving
        thread (refit / continued training) replaces the resident model
        under the model lock with a SINGLE version bump, so every
        concurrent PredictSession snapshot sees either the old ensemble
        or the new one whole — never a half-committed pack. Scores and
        validation trackers are NOT rebuilt (serving boosters have no
        training state to keep consistent; call _rebuild_scores yourself
        if you adopt into a live training booster).

        Returns an opaque rollback token for :meth:`restore`.
        """
        with self._cache_lock:
            snap = (list(self.models), self.init_scores.copy(), self.iter_,
                    self.best_iteration)
            self.models = list(other.models)
            self.init_scores = np.asarray(other.init_scores,
                                          np.float64).copy()
            self.iter_ = int(other.iter_)
            # the adopted model's stored early-stop cap replaces ours:
            # a booster loaded from a 6-tree publish would otherwise keep
            # best_iteration=6 forever and silently truncate every later
            # adopted model with more trees at predict time
            self.best_iteration = int(getattr(other, "best_iteration", -1))
            self._bump_model_version()
        return snap

    def restore(self, snapshot: tuple) -> None:
        """Roll back to a model captured by :meth:`adopt` (same single
        version-bump atomicity as the promotion itself)."""
        models, init_scores, it, best_it = snapshot
        with self._cache_lock:
            self.models = list(models)
            self.init_scores = np.asarray(init_scores, np.float64).copy()
            self.iter_ = int(it)
            self.best_iteration = int(best_it)
            self._bump_model_version()

    def _packed_model(self, start: int, end: int):
        """Device-resident ``PackedSplits`` for iterations [start, end).

        Cached behind the model-version token so repeat predicts pay zero
        host re-packs and zero uploads (``serve/pack_build`` vs
        ``serve/pack_hit`` counters); continued training, rollback and
        score rebuilds bump the version and naturally invalidate. All
        PredictSessions over this booster share the cache."""
        from .obs import telemetry
        from .ops.predict import pack_splits

        # the whole lookup-or-build runs under the model lock: the key
        # read, the models slice and the store must see one consistent
        # (models, version) pair or a concurrent commit tears the pack
        with self._cache_lock:
            cache = getattr(self, "_pack_cache", None)
            if cache is None or not isinstance(cache, dict):
                cache = self._pack_cache = {}
            key = (start, end, self._model_version)
            hit = cache.get(key)
            if hit is not None:
                telemetry.count("serve/pack_hit")
                return hit
            if len(cache) > 16:
                cache.clear()
            telemetry.count("serve/pack_build")
            K = self.num_tree_per_iteration
            hit = cache[key] = pack_splits(self.models[start * K:end * K],
                                           num_class=K)
            return hit

    def _forest_knob(self) -> str:
        """Resolved ``tpu_forest_kernel`` value for serving sessions:
        the learner's build-time resolution when this booster trained in
        process, else the configured value (``auto`` resolves ``off`` —
        the kernel's Mosaic lowering is unvalidated on hardware; see
        scripts/forest_bisect.py)."""
        # sessions call this from serving threads while reset_parameter
        # may swap the learner on the training thread
        with self._cache_lock:
            lr = getattr(self, "learner", None)
        v = getattr(lr, "_forest_kernel", None)
        if v in ("on", "off"):
            return v
        cfg = getattr(self.config, "tpu_forest_kernel", "auto")
        return "off" if cfg == "auto" else cfg

    def _forest_model(self, start: int, end: int):
        """Device-resident BIN-space ``ForestPack`` for [start, end), or
        ``None`` when the forest path is structurally ineligible (no
        constructed train_set to supply bin mappers, splits on unmapped
        features, node tables over the VMEM budget).

        Cached behind the model-version token exactly like
        ``_packed_model`` (``serve/forest_build`` / ``serve/forest_hit``
        counters); ineligibility is cached too, so a hot predict path
        never re-derives it."""
        from .obs import telemetry
        from .ops.forest import (FOREST_VMEM_BUDGET, forest_pack,
                                 forest_table_bytes)

        with self._cache_lock:
            cache = getattr(self, "_forest_cache", None)
            if cache is None:
                cache = self._forest_cache = {}
            key = (start, end, self._model_version)
            hit = cache.get(key)
            if hit is not None:
                telemetry.count("serve/forest_hit")
                return None if hit[0] == "ineligible" else hit[1]
            if len(cache) > 16:
                cache.clear()
            ds = self.train_set
            why = None
            entry = None
            if ds is None:
                why = "no constructed train_set (bin mappers unavailable)"
            else:
                try:
                    telemetry.count("serve/forest_build")
                    K = self.num_tree_per_iteration
                    fp, has_cat, has_linear = forest_pack(
                        self.models[start * K:end * K], ds, num_class=K)
                    tbytes = forest_table_bytes(fp)
                    if tbytes > FOREST_VMEM_BUDGET:
                        why = ("node tables %d B exceed the %d B VMEM "
                               "budget" % (tbytes, FOREST_VMEM_BUDGET))
                    else:
                        entry = (fp, has_cat, has_linear)
                except ValueError as exc:
                    why = str(exc)
            if entry is None:
                cache[key] = ("ineligible", why)
                telemetry.record("forest_ineligible", dedupe_key=why,
                                 reason=why)
                return None
            cache[key] = ("ok", entry)
            return entry

    def _predict_session(self, start: int, end: int):
        """Lazily created serving session per iteration range (the device
        predict path of ``_raw_scores_range``). Sessions hold only bucket
        warm-state; the pack itself lives in the shared version-keyed
        ``_packed_model`` cache."""
        from .serve.session import PredictSession

        with self._cache_lock:
            cache = getattr(self, "_serve_sessions", None)
            if cache is None:
                cache = self._serve_sessions = {}
            sess = cache.get((start, end))
            if sess is None:
                if len(cache) > 32:
                    cache.clear()
                sess = cache[(start, end)] = PredictSession(
                    self, start_iteration=start, num_iteration=end - start)
            return sess

    def _raw_scores(self, X: np.ndarray, start: int, end: int) -> np.ndarray:
        """Ensemble raw scores with optional prediction early stopping
        (reference: src/boosting/prediction_early_stop.cpp — rows whose
        margin exceeds pred_early_stop_margin stop accumulating trees,
        checked every pred_early_stop_freq iterations)."""
        cfg = self.config
        K = self.num_tree_per_iteration
        es = bool(cfg.pred_early_stop) and self.objective is not None \
            and (self.objective.name in ("binary",)
                 or (K > 1 and "multiclass" in self.objective.name))
        if not es:
            return self._raw_scores_range(X, start, end)
        freq = max(1, int(cfg.pred_early_stop_freq))
        margin_thr = float(cfg.pred_early_stop_margin)
        n = X.shape[0]
        score = np.zeros((n, K), dtype=np.float64)
        active = np.ones(n, dtype=bool)
        # the margin the reference thresholds is that of the FINAL score,
        # which includes boost_from_average init scores
        init = self.init_scores[None, :K]
        for b0 in range(start, end, freq):
            if not active.any():
                break
            b1 = min(end, b0 + freq)
            sub = X[active]
            score[active] += self._raw_scores_range(sub, b0, b1)
            full = score[active] + init
            if K == 1:
                margin = 2.0 * np.abs(full[:, 0])
            else:
                top2 = np.partition(full, K - 2, axis=1)[:, K - 2:]
                margin = np.max(top2, axis=1) - np.min(top2, axis=1)
            still = margin <= margin_thr
            idx = np.flatnonzero(active)
            active[idx[~still]] = False
        return score

    def _raw_scores_range(self, X: np.ndarray, start: int,
                          end: int) -> np.ndarray:
        """Ensemble raw scores (N, K) over model range [start*K, end*K).

        Large batches route on device (reference analog:
        src/application/predictor.hpp batch prediction); small batches walk
        the host trees — a device launch costs ~100 ms behind the tunnel.
        """
        K = self.num_tree_per_iteration
        n = X.shape[0]
        # snapshot under the model lock: the online trainer shadow-scores
        # candidates from its worker thread while promotions mutate models
        with self._cache_lock:
            models = self.models[start * K:end * K]
        if n >= self.DEVICE_PREDICT_MIN_ROWS and models:
            return self._predict_session(start, end).raw_scores(X)
        score = np.zeros((n, K), dtype=np.float64)
        for i, t in enumerate(models):
            score[:, (start * K + i) % K] += t.predict(X)
        return score

    def predict(self, X: np.ndarray, *, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False) -> np.ndarray:
        self.finish_fused("predict")
        X = np.asarray(X, dtype=np.float64)
        n = X.shape[0]
        K = self.num_tree_per_iteration
        with self._cache_lock:
            total_iters = len(self.models) // max(K, 1)
            if num_iteration is None or num_iteration <= 0:
                num_iteration = total_iters - start_iteration
            end = min(total_iters, start_iteration + num_iteration)
            leaf_models = self.models[start_iteration * K:end * K] \
                if pred_leaf else None
        if pred_leaf:
            out = np.zeros((n, (end - start_iteration) * K), dtype=np.int32)
            for i, t in enumerate(leaf_models):
                out[:, i] = t.predict_leaf_index(X)
            return out
        score = self._raw_scores(X, start_iteration, end)
        score = score + self.init_scores[None, :K]
        if not raw_score and self.objective is not None:
            score = np.asarray(self.objective.convert_output(jnp.asarray(score)))
        if K == 1:
            return score.ravel()
        return score

    # --------------------------------------------------------------- model IO
    def model_to_string(self, num_iteration: int = -1) -> str:
        """(reference: gbdt_model_text.cpp:400 SaveModelToString)"""
        self.finish_fused("model_to_string")
        cfg = self.config
        K = self.num_tree_per_iteration
        # snapshot the model list under the lock: the online trainer
        # serializes the serving booster from its worker thread (refit
        # round-trips through the model string) while promotions swap it
        with self._cache_lock:
            models = list(self.models)
            init_scores = self.init_scores.copy()
        total_iters = len(models) // max(K, 1)
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total_iters
        end = min(total_iters, num_iteration) * K
        lines = [
            "tree",
            "version=v3",
            "boosting=%s" % self.name,
            "objective=%s" % self._objective_string(),
            "num_class=%d" % self.num_class,
            "num_tree_per_iteration=%d" % K,
            "init_score=%s" % " ".join("%.17g" % v for v in init_scores),
            "max_feature_idx=%d" % (self.train_set.num_total_features - 1
                                    if self.train_set else -1),
            "feature_names=%s" % " ".join(self.train_set.feature_names
                                          if self.train_set else []),
            "best_iteration=%d" % self.best_iteration,
            "",
        ]
        for i, tree in enumerate(models[:end]):
            lines.append("Tree=%d" % i)
            lines.append(tree.to_text())
            lines.append("")
        lines.append("end of trees")
        # saved_feature_importance_type selects the importance measure
        # written into the model file (reference: gbdt_model_text.cpp:100
        # SaveModelToString -> FeatureImportance(.., type))
        itype = "gain" if int(cfg.saved_feature_importance_type) == 1 \
            else "split"
        try:
            imps = self.feature_importance(itype, num_iteration)
            names = self.train_set.feature_names if self.train_set \
                else getattr(self, "_feature_names", [])
            pairs = [(float(v), names[i] if i < len(names) else
                      "Column_%d" % i) for i, v in enumerate(imps) if v > 0]
            pairs.sort(key=lambda p: -p[0])
            lines.append("")
            lines.append("feature_importances:")
            for v, name in pairs:
                lines.append("%s=%.17g" % (name, v)
                             if itype == "gain" else "%s=%d" % (name, int(v)))
        except Exception:  # importances are informational; never block IO
            pass
        return "\n".join(lines)

    def to_if_else_cpp(self, num_iteration: int = -1) -> str:
        """Standalone C++ prediction source for the whole ensemble
        (reference: gbdt_model_text.cpp:258 ModelToIfElse; also its model-
        correctness regression harness). Emits per-tree if-else functions,
        a PredictRaw accumulator (init scores included) and extern-C
        single-row entry points so the file both drops into user code and
        compiles into a test harness."""
        self.finish_fused("to_if_else_cpp")
        K = self.num_tree_per_iteration
        total_iters = len(self.models) // max(K, 1)
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total_iters
        end = min(total_iters, num_iteration) * K
        parts = [
            "// generated by lightgbm_tpu task=convert_model",
            "#include <cmath>",
            "#include <cstdint>",
            "#include <algorithm>",
            "",
            "static inline bool cat_in(int64_t v, const int64_t* arr, "
            "int n) {",
            "  return std::binary_search(arr, arr + n, v);",
            "}",
            "",
        ]
        for i, tree in enumerate(self.models[:end]):
            parts.append(tree.to_if_else(i))
            parts.append("")
        init = ", ".join("%.17g" % v for v in self.init_scores[:max(K, 1)])
        parts += [
            "static const int kNumClass = %d;" % max(K, 1),
            "static const int kNumTrees = %d;" % end,
            "static const double kInitScore[%d] = {%s};" % (max(K, 1), init),
            "",
            "typedef double (*TreeFn)(const double*);",
            "static const TreeFn kTrees[%d] = {%s};" % (
                max(end, 1),
                ", ".join("PredictTree%d" % i for i in range(end)) or "0"),
            "",
            "extern \"C\" void PredictRaw(const double* arr, double* out) {",
            "  for (int k = 0; k < kNumClass; ++k) out[k] = kInitScore[k];",
            "  for (int i = 0; i < kNumTrees; ++i) {",
            "    out[i % kNumClass] += kTrees[i](arr);",
            "  }",
            "}",
            "",
        ]
        obj = self.objective.name if self.objective else ""
        if obj == "binary":
            sig = self.config.sigmoid
            transform = ("  out[0] = 1.0 / (1.0 + std::exp(-%.17g * "
                         "out[0]));" % sig)
        elif obj in ("multiclassova", "ova"):
            sig = self.config.sigmoid
            transform = ("  for (int k = 0; k < kNumClass; ++k) out[k] = "
                         "1.0 / (1.0 + std::exp(-%.17g * out[k]));" % sig)
        elif obj in ("multiclass", "softmax"):
            transform = (
                "  double m = out[0];\n"
                "  for (int k = 1; k < kNumClass; ++k) m = std::max(m, "
                "out[k]);\n"
                "  double s = 0;\n"
                "  for (int k = 0; k < kNumClass; ++k) { out[k] = "
                "std::exp(out[k] - m); s += out[k]; }\n"
                "  for (int k = 0; k < kNumClass; ++k) out[k] /= s;")
        else:
            transform = "  // identity output transform"
        parts += [
            "extern \"C\" void Predict(const double* arr, double* out) {",
            "  PredictRaw(arr, out);",
            transform,
            "}",
            "",
        ]
        return "\n".join(parts)

    def _objective_string(self) -> str:
        obj = self.objective.name if self.objective else self.config.objective
        if obj in ("multiclass", "multiclassova"):
            return "%s num_class:%d" % (obj, self.num_class)
        return obj

    def save_model(self, filename: str, num_iteration: int = -1) -> None:
        with open(filename, "w") as f:
            f.write(self.model_to_string(num_iteration))

    @classmethod
    def model_from_string(cls, s: str, config: Optional[Config] = None) -> "GBDT":
        config = config or Config()
        header, _, rest = s.partition("Tree=")
        kv: Dict[str, str] = {}
        for line in header.splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
        obj_str = kv.get("objective", "regression").split()
        config.objective = obj_str[0]
        for tok in obj_str[1:]:
            if tok.startswith("num_class:"):
                config.num_class = int(tok.split(":")[1])
        booster_cls = {"gbdt": cls, "dart": DART, "rf": RF}.get(
            kv.get("boosting", "gbdt"), cls)
        model = booster_cls.__new__(booster_cls)
        # run the full subclass constructor chain so DART/RF state
        # (_tree_weights/_drop_rng/_init_score_dev) exists for continued
        # training on a loaded model
        booster_cls.__init__(model, config, None)
        model.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", 1))
        model.num_class = int(kv.get("num_class", 1))
        init = kv.get("init_score", "0").split()
        model.init_scores = np.asarray([float(v) for v in init], dtype=np.float64)
        model.best_iteration = int(kv.get("best_iteration", -1))
        model.objective = create_objective(config)
        # default metrics follow the objective so a loaded model can
        # evaluate valid sets (reference: metric defaults from objective)
        model.metrics = create_metrics(config, model.objective.name)
        model._feature_names = kv.get("feature_names", "").split()
        body = "Tree=" + rest
        for block in body.split("Tree=")[1:]:
            block = block.split("end of trees")[0]
            lines = block.strip().splitlines()[1:]  # drop the index line remnant
            # first line of block is "<idx>\n..." — strip leading index
            model.models.append(Tree.from_text("\n".join(lines)))
        model.iter_ = len(model.models) // max(model.num_tree_per_iteration, 1)
        return model

    def dump_json(self, num_iteration: int = -1) -> str:
        self.finish_fused("dump_json")
        K = self.num_tree_per_iteration
        total_iters = len(self.models) // max(K, 1)
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total_iters
        end = min(total_iters, num_iteration) * K
        d = {
            "name": "tree",
            "version": "v3",
            "objective": self._objective_string(),
            "num_class": self.num_class,
            "num_tree_per_iteration": K,
            "init_score": self.init_scores.tolist(),
            "tree_info": [t.to_dict() for t in self.models[:end]],
        }
        return json.dumps(d)

    @property
    def current_iteration(self) -> int:
        self.finish_fused("current_iteration")
        return self.iter_

    def num_trees(self) -> int:
        self.finish_fused("num_trees")
        return len(self.models)

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        """(reference: GBDT::FeatureImportance, gbdt.cpp)"""
        self.finish_fused("feature_importance")
        with self._cache_lock:
            models = list(self.models)
        nf = self.train_set.num_total_features if self.train_set else (
            max((t.split_feature.max() for t in models
                 if t.num_leaves > 1), default=-1) + 1)
        imp = np.zeros(nf, dtype=np.float64)
        K = self.num_tree_per_iteration
        end = len(models) if iteration <= 0 else min(
            len(models), iteration * K)
        for t in models[:end]:
            if t.num_leaves <= 1:
                continue
            for r in range(t.num_internal):
                if importance_type == "split":
                    imp[t.split_feature[r]] += 1
                else:
                    imp[t.split_feature[r]] += max(0.0, float(t.split_gain[r]))
        return imp


class DART(GBDT):
    """Dropout boosting (reference: src/boosting/dart.hpp)."""

    name = "dart"

    def __init__(self, config: Config, train_set: Optional[BinnedDataset],
                 comm_axis: Optional[str] = None) -> None:
        super().__init__(config, train_set, comm_axis)
        self._tree_weights: List[float] = []
        self._drop_rng = np.random.RandomState(config.drop_seed)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        cfg = self.config
        K = self.num_tree_per_iteration
        # ---- select and subtract the drop set (dart.hpp:97 DroppingTrees) ----
        drop: List[int] = []
        if self._drop_rng.rand() >= cfg.skip_drop and self.iter_ > 0:
            n_iters = self.iter_
            if cfg.uniform_drop:
                sel = self._drop_rng.rand(n_iters) < cfg.drop_rate
                drop = list(np.flatnonzero(sel))
            else:
                p = min(1.0, cfg.drop_rate)
                k_drop = min(cfg.max_drop, np.random.RandomState(
                    cfg.drop_seed + self.iter_).binomial(n_iters, p))
                if k_drop > 0:
                    drop = list(self._drop_rng.choice(n_iters, size=k_drop,
                                                      replace=False))
        for it_idx in drop:
            for k in range(K):
                tree = self.models[it_idx * K + k]
                self._apply_tree_delta(tree, k, -1.0)
        k_cnt = len(drop)
        # ---- train on the reduced score ----
        stop = super().train_one_iter(grad, hess)
        if stop:
            # restore the dropped trees untouched so score trackers stay
            # consistent when no tree could be grown
            for it_idx in drop:
                for k in range(K):
                    self._apply_tree_delta(self.models[it_idx * K + k], k, 1.0)
            return stop
        # ---- normalize (dart.hpp:65 Normalize) ----
        if not stop:
            norm = 1.0 / (k_cnt + 1.0)
            if cfg.xgboost_dart_mode:
                norm = cfg.learning_rate / (k_cnt + cfg.learning_rate)
            # normalization mutates committed trees in place AFTER the
            # super() bump — run it (and the re-bump) under the model
            # lock so a concurrent pack never captures half-rescaled
            # leaf values, then bump so stale packs invalidate
            with self._cache_lock:
                for k in range(K):
                    tree = self.models[-K + k]
                    # remove the freshly-added (unnormalized)
                    # contribution, rescale
                    self._apply_tree_delta(tree, k, norm - 1.0)
                    tree.apply_shrinkage(norm)
                if k_cnt > 0:
                    factor = k_cnt / (k_cnt + 1.0)
                    if cfg.xgboost_dart_mode:
                        factor = k_cnt / (k_cnt + cfg.learning_rate)
                    for it_idx in drop:
                        for k in range(K):
                            tree = self.models[it_idx * K + k]
                            self._apply_tree_delta(tree, k, factor)
                            tree.apply_shrinkage(factor)
                self._bump_model_version()
        return stop

    def _shrinkage_rate(self, log: TreeLog) -> float:
        # DART applies learning_rate at train time, normalization after
        return float(self.config.learning_rate)

    def _apply_tree_delta(self, tree: Tree, class_id: int, scale: float) -> None:
        """Add ``scale`` × tree's contribution to train/valid scores."""
        vals, leaf = self._route_tree_device(tree, self.train_set)
        self.train_score.add(vals, leaf, class_id,
                             self.num_tree_per_iteration, scale=scale)
        for _, vset, vscore in self.valid_sets:
            vvals, vleaf = self._route_tree_device(tree, vset)
            vscore.add(vvals, vleaf, class_id,
                       self.num_tree_per_iteration, scale=scale)


class RF(GBDT):
    """Random forest mode (reference: src/boosting/rf.hpp): bagging is
    mandatory, no shrinkage, scores are the average of tree outputs, and
    gradients are always computed at the (constant) init score."""

    name = "rf"

    def __init__(self, config: Config, train_set: Optional[BinnedDataset],
                 comm_axis: Optional[str] = None) -> None:
        super().__init__(config, train_set, comm_axis)
        self._init_score_dev = None
        if train_set is not None:
            self._init_score_dev = self.train_score.score

    def train_one_iter(self, grad=None, hess=None) -> bool:
        if grad is None:
            g, h = self._grad_fn(self._init_score_dev, jnp.int32(self.iter_))
        else:
            g, h = jnp.asarray(grad, jnp.float32), jnp.asarray(hess, jnp.float32)
        it = self.iter_
        self._bagging(it, g, h)
        fmask = self._feature_mask(it)
        any_ok = False
        for k in range(self.num_tree_per_iteration):
            ghc = self._tree_channels(g, h, k)
            key = jax.random.fold_in(self._key, it * 131 + k)
            log = self.learner.train(ghc, fmask, key,
                                     jnp.asarray(self._cegb_used))
            tree = self.learner.log_to_tree(log)
            # averaged score: rescale previous sum then add (ref rf.hpp)
            with self._cache_lock:
                self.models.append(tree)
            self._note_used_features(tree)
            self._accumulate_avg(tree, log, k)
            if tree.num_leaves > 1:
                any_ok = True
        with self._cache_lock:
            self.iter_ += 1
            self._bump_model_version()
        return not any_ok

    def _accumulate_avg(self, tree: Tree, log: TreeLog, class_id: int) -> None:
        it = self.iter_  # completed iterations before this one
        K = self.num_tree_per_iteration
        # running average over iterations: new_avg = (old*it + tree)/(it+1)
        if self.num_class > 1:
            init_col = self.init_scores[class_id]
            old = self.train_score.score[:, class_id] - init_col
            lv = jnp.asarray(tree.leaf_value, jnp.float32)
            new = (old * it + leaf_values_by_row(lv, log.row_leaf, lv.shape[0])) \
                / (it + 1)
            self.train_score.score = self.train_score.score.at[:, class_id].set(
                new + init_col)
        else:
            old = self.train_score.score - self.init_scores[0]
            lv = jnp.asarray(tree.leaf_value, jnp.float32)
            new = (old * it + leaf_values_by_row(lv, log.row_leaf, lv.shape[0])) \
                / (it + 1)
            self.train_score.score = new + self.init_scores[0]
        for _, vset, vscore in self.valid_sets:
            vleaf = assign_leaves(
                self._valid_bins(vset), log,
                has_categorical=self.learner.hp.has_categorical,
                bundle=self.learner.bundle)
            lv = jnp.asarray(tree.leaf_value, jnp.float32)
            vals = leaf_values_by_row(lv, vleaf, lv.shape[0])
            if self.num_class > 1:
                init_col = self.init_scores[class_id]
                old = vscore.score[:, class_id] - init_col
                vscore.score = vscore.score.at[:, class_id].set(
                    (old * it + vals) / (it + 1) + init_col)
            else:
                old = vscore.score - self.init_scores[0]
                vscore.score = (old * it + vals) / (it + 1) + self.init_scores[0]

    def predict(self, X, *, raw_score=False, start_iteration=0,
                num_iteration=-1, pred_leaf=False):
        X = np.asarray(X, dtype=np.float64)
        n = X.shape[0]
        K = self.num_tree_per_iteration
        total_iters = len(self.models) // max(K, 1)
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total_iters - start_iteration
        end = min(total_iters, start_iteration + num_iteration)
        if pred_leaf:
            return super().predict(X, raw_score=raw_score,
                                   start_iteration=start_iteration,
                                   num_iteration=num_iteration, pred_leaf=True)
        cnt = max(1, end - start_iteration)
        score = self._raw_scores(X, start_iteration, end) / cnt
        score = score + self.init_scores[None, :K]
        if not raw_score and self.objective is not None:
            score = np.asarray(self.objective.convert_output(jnp.asarray(score)))
        return score.ravel() if K == 1 else score


def create_boosting(config: Config, train_set: Optional[BinnedDataset],
                    comm_axis: Optional[str] = None) -> GBDT:
    """Factory (reference: src/boosting/boosting.cpp:35 CreateBoosting)."""
    kind = config.boosting
    if kind in ("gbdt", "gbrt", "goss"):
        return GBDT(config, train_set, comm_axis)
    if kind == "dart":
        return DART(config, train_set, comm_axis)
    if kind in ("rf", "random_forest"):
        return RF(config, train_set, comm_axis)
    Log.fatal("Unknown boosting type: %s", kind)
