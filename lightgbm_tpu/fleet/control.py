"""Region-scale fleet control plane: the remote WRITE surface.

PRs 11–15 made the fleet durable and replicated, but every writer still
touched one disk: the lease, the event log and the artifacts live in
one ``FleetStore`` directory, ``/fleet/*`` over HTTP is read-only, and
each replica follows exactly one endpoint. This module removes the
shared-filesystem requirement from every remaining role:

- :class:`RemoteWriteStore` — a trainer's store over HTTP. It
  duck-types the full write surface :class:`~..online.trainer.
  OnlineTrainer` uses (``acquire_lease`` / ``renew_lease`` /
  ``release_lease``, fenced ``publish``, ``append_ingest`` /
  ``append_gate``, ``compact``, ``events`` replay, snapshot loads), so
  a trainer on a machine that shares NOTHING with the store host runs
  the identical lease/fence/replay code as a local one. Fencing is
  enforced server-side: the client stamps its (holder, epoch) into
  every ``POST /fleet/publish`` body and the store host re-checks the
  lease under its own lock — a zombie's stale epoch is answered 409
  (never retried; retrying a fence verdict would just hammer the new
  leader) and surfaces here as the same :class:`~.store.
  StaleLeaseError` the local path raises.
- :class:`EndpointSelector` + :class:`MultiEndpointStore` — the read
  side's failover. A replica gets a LIST of ``fleet_urls``; the
  selector keeps a sticky current endpoint, puts failing ones in
  capped-exponential cooldown, and ranks the rest by the liveness
  evidence the PR 15 heartbeat sidecars already publish (``/fleet/
  status`` head version + freshest heartbeat age). ``ReplicaWatcher``
  code is untouched: version tokens are global, so adopting each
  publish exactly once holds no matter which endpoint served it.
- :class:`IngestForwarder` — labeled traffic hitting ANY node is
  relayed to whichever node currently holds the trainer lease. The
  lease record itself advertises the holder's serving URL (written at
  acquire/renew time), responses carry a ``leader_hint``, and the
  redirect chain is bounded by an ``X-Fleet-Hops`` header so a stale
  hint loop degrades to 503, not an infinite relay.

Everything here is stdlib HTTP over the PR 14 transport (same retries,
same capped deterministic-jitter backoff, same chaos points), entirely
CPU-testable.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from ..obs import telemetry
from ..utils.log import LightGBMError, Log
from .store import (CorruptArtifactError, StaleLeaseError, _verify_snapshot)
from .transport import RemoteStore, TransportError, _NotFound, _Rejected

_LEASE = "/fleet/lease"
_PUBLISH = "/fleet/publish"
_INGEST = "/fleet/ingest"
_GATE = "/fleet/gate"
_COMPACT = "/fleet/compact"
_EVENTS = "/fleet/events"
_SNAPSHOT = "/fleet/snapshot/%d"
_STATUS = "/fleet/status"

#: forwarded-ingest hop header: bounds the redirect chain so a stale
#: leader hint cycling between two nodes 503s instead of relaying forever
HOPS_HEADER = "X-Fleet-Hops"


class RemoteWriteStore(RemoteStore):
    """Full fleet-store write surface over HTTP.

    Extends the read-only :class:`~.transport.RemoteStore` with every
    method the online trainer drives a local :class:`~.store.FleetStore`
    with, so ``OnlineTrainer(store=RemoteWriteStore(url))`` needs no
    trainer changes: lease acquire/renew/release round-trip ``POST
    /fleet/lease``; ``publish`` uploads the whole model with its sha256
    + byte length (the host verifies the upload before it verifies the
    fence — a torn upload is 400, a zombie is 409); ingest/gate appends
    and compaction requests are relayed verbatim; ``events()`` replay
    and snapshot loads come back over GET. The fence is client-side
    state (`set_fence`) stamped into each publish body — enforcement
    happens on the store host, under the same store lock as local
    publishes, so a remote zombie and a local zombie die identically.
    """

    def __init__(self, base_url: str, **kwargs: Any) -> None:
        super().__init__(base_url, **kwargs)
        self._fence_w: Optional[Tuple[str, int]] = None
        self._last_version = 0
        self._publishes_sent = 0
        self._ingest_rows_sent = 0

    # ------------------------------------------------------------------ lease
    def _lease_op(self, op: str, body: Dict[str, Any]) -> Dict[str, Any]:
        body = dict(body)
        body["op"] = op
        data = json.dumps(body, sort_keys=True).encode("utf-8")
        try:
            doc = json.loads(self._request(_LEASE, data=data)
                             .decode("utf-8"))
        except _NotFound:
            raise TransportError(
                "%s%s not found: the store host predates the fleet "
                "control plane (no remote lease ops)" % (self._base, _LEASE))
        return doc if isinstance(doc, dict) else {}

    def acquire_lease(self, holder: str, ttl_s: float,
                      url: Optional[str] = None) -> Optional[int]:
        """Remote lease acquisition. Returns the new fencing epoch, or
        None while another live holder has it — same contract as the
        local store (the host runs the same O_EXCL-guarded code)."""
        doc = self._lease_op("acquire", {
            "holder": str(holder), "ttl_s": float(ttl_s),
            "url": str(url) if url else None})
        epoch = doc.get("epoch")
        return int(epoch) if epoch is not None else None

    def renew_lease(self, holder: str, epoch: int, ttl_s: float,
                    url: Optional[str] = None) -> bool:
        doc = self._lease_op("renew", {
            "holder": str(holder), "epoch": int(epoch),
            "ttl_s": float(ttl_s), "url": str(url) if url else None})
        return bool(doc.get("ok"))

    def release_lease(self, holder: str, epoch: int) -> bool:
        doc = self._lease_op("release", {
            "holder": str(holder), "epoch": int(epoch)})
        return bool(doc.get("ok"))

    def lease_state(self) -> Dict[str, Any]:
        doc = self._lease_op("state", {})
        lease = doc.get("lease")
        if isinstance(lease, dict):
            return lease
        return {"held": False, "holder": None, "epoch": 0,
                "expires_ts": 0.0, "url": None}

    def set_fence(self, holder: str, epoch: int) -> None:
        with self._lock:
            self._fence_w = (str(holder), int(epoch))

    def clear_fence(self) -> None:
        with self._lock:
            self._fence_w = None

    # ---------------------------------------------------------------- publish
    def publish(self, model_str: str, event: str = "promotion",
                meta: Optional[Dict[str, Any]] = None) -> int:
        """Upload + publish one whole model. The body carries the
        model's sha256 and byte length (host verifies before writing —
        a torn upload can never become an artifact) and this client's
        fence; a 409 from the host's fence check raises the same
        :class:`StaleLeaseError` a fenced-off local publish does."""
        data = model_str.encode("utf-8")
        with self._lock:
            fence = self._fence_w
        body = {
            "model": model_str, "event": str(event), "meta": meta,
            "sha256": hashlib.sha256(data).hexdigest(),
            "bytes": len(data),
            "holder": fence[0] if fence else None,
            "lease_epoch": fence[1] if fence else 0,
        }
        payload = json.dumps(body, sort_keys=True).encode("utf-8")
        try:
            doc = json.loads(
                self._request(_PUBLISH, data=payload,
                              no_retry=(400, 409)).decode("utf-8"))
        except _Rejected as exc:
            verdict = exc.doc()
            if exc.code == 409:
                telemetry.count("fleet/stale_publishes_blocked_remote")
                raise StaleLeaseError(
                    "remote publish fenced off by %s: %s (leader hint: "
                    "%s)" % (self._base, verdict.get("error"),
                             verdict.get("leader_hint")))
            raise CorruptArtifactError(
                "remote publish rejected by %s: %s"
                % (self._base, verdict.get("error")))
        version = int(doc.get("version", 0))
        with self._lock:
            self._publishes_sent += 1
            if version > self._last_version:
                self._last_version = version
        return version

    # ---------------------------------------------------------------- appends
    def append_ingest(self, X, y) -> None:
        import numpy as np
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        y = np.asarray(y, np.float64).ravel()
        body = json.dumps({"rows": X.tolist(), "labels": y.tolist()},
                          sort_keys=True).encode("utf-8")
        self._request(_INGEST, data=body)
        with self._lock:
            self._ingest_rows_sent += int(len(y))

    def append_gate(self, result: str, wins: int, consumed_rows: int,
                    losses: Optional[Dict[str, float]] = None) -> None:
        body = json.dumps({
            "result": str(result), "wins": int(wins),
            "consumed_rows": int(consumed_rows), "losses": losses},
            sort_keys=True).encode("utf-8")
        self._request(_GATE, data=body)

    # ------------------------------------------------------------- compaction
    def compact(self, *, watermark: int, wins: int, keep_rows: int,
                keep_artifacts: int = 0,
                snapshot_rows: int = 0) -> Dict[str, Any]:
        body = json.dumps({
            "watermark": int(watermark), "wins": int(wins),
            "keep_rows": int(keep_rows),
            "keep_artifacts": int(keep_artifacts),
            "snapshot_rows": int(snapshot_rows)},
            sort_keys=True).encode("utf-8")
        doc = json.loads(self._request(_COMPACT, data=body)
                         .decode("utf-8"))
        return doc if isinstance(doc, dict) else {}

    # ----------------------------------------------------------------- replay
    def events(self, kind: Optional[str] = None
               ) -> Iterator[Dict[str, Any]]:
        """The store host's full event log (one GET). Cold-boot replay
        for a remote standby; with snapshot compaction on, the log is a
        compact record + publishes + tail, so this stays small."""
        try:
            doc = json.loads(self._request(_EVENTS).decode("utf-8"))
        except _NotFound:
            return
        for e in (doc.get("events") or []) if isinstance(doc, dict) else []:
            if isinstance(e, dict) and (kind is None
                                        or e.get("kind") == kind):
                yield e

    def log_bytes(self) -> int:
        try:
            doc = json.loads(self._request(_STATUS).decode("utf-8"))
        except (_NotFound, TransportError, ValueError):
            return 0
        return int(doc.get("log_bytes", 0)) if isinstance(doc, dict) else 0

    def load_snapshot(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Download + verify the snapshot behind one compact record —
        the remote standby's one-blob bootstrap read."""
        snap = record.get("snapshot") or {}
        data = self._request(_SNAPSHOT % int(snap.get("id", 0)))
        _verify_snapshot(record, data)
        return json.loads(data.decode("utf-8"))

    def snapshot_chunks(self, record: Dict[str, Any]
                        ) -> List[Tuple[int, int, Dict[str, Any]]]:
        """Same degrade-to-empty contract as the local store: a missing
        or corrupt snapshot costs buffered rows, never misaligns replay
        (the compact record's ``row_base`` already sits past it)."""
        snap = record.get("snapshot")
        if not isinstance(snap, dict):
            return []
        try:
            doc = self.load_snapshot(record)
        except (_NotFound, TransportError, ValueError,
                CorruptArtifactError) as exc:
            telemetry.count("fleet/snapshot_load_failures")
            Log.warning("fleet: remote snapshot s%06d unreadable (%s); "
                        "replay continues degraded",
                        int(snap.get("id", 0)), exc)
            return []
        out: List[Tuple[int, int, Dict[str, Any]]] = []
        for c in doc.get("chunks", []):
            ev = c.get("event") or {}
            lo = int(c.get("lo", 0))
            out.append((lo, lo + int(ev.get("n", 0)), ev))
        return out

    # ------------------------------------------------------------------ state
    def state(self) -> Dict[str, Any]:
        doc = super().state()
        with self._lock:
            doc["last_published_version"] = self._last_version
            doc["publishes_sent"] = self._publishes_sent
            doc["ingest_rows_sent"] = self._ingest_rows_sent
            doc["write_surface"] = True
        return doc


class EndpointSelector:
    """Sticky-with-cooldown choice over a list of fleet endpoints.

    The current endpoint stays current until it fails (stickiness keeps
    the replica's polls on one host's warm caches); a failure puts it
    in capped-exponential cooldown (``base * 2^(failures-1)``, capped)
    and the next candidate takes over. :meth:`candidates` always yields
    EVERY endpoint — cooled-down ones last, ordered by soonest expiry —
    so a total outage degrades to one failed sweep per poll, never to
    an endpoint silently dropped forever. Liveness evidence from the
    heartbeat sidecars (``/fleet/status`` head version + freshest
    heartbeat age) feeds :meth:`observe`, which prefers the most
    caught-up endpoint on the next reorder. Thread-safe; time source is
    monotonic (cooldowns are durations, not wall-clock stamps).
    """

    def __init__(self, urls: Sequence[str], *,
                 cooldown_base_s: float = 0.25,
                 cooldown_max_s: float = 8.0) -> None:
        urls = [str(u).rstrip("/") for u in urls]
        if not urls:
            raise LightGBMError("EndpointSelector needs >= 1 url")
        if len(set(urls)) != len(urls):
            raise LightGBMError("duplicate fleet urls: %r" % (urls,))
        self._lock = threading.Lock()
        self._urls = list(urls)
        self._current = urls[0]
        self._cool_base = float(cooldown_base_s)
        self._cool_max = float(cooldown_max_s)
        self._failures: Dict[str, int] = {u: 0 for u in urls}
        self._cool_until: Dict[str, float] = {u: 0.0 for u in urls}
        #: liveness evidence: url -> (head_version, -heartbeat_age_s)
        self._score: Dict[str, Tuple[int, float]] = {}
        self._switches = 0

    @property
    def urls(self) -> List[str]:
        return list(self._urls)

    def current(self) -> str:
        with self._lock:
            return self._current

    def candidates(self) -> List[str]:
        """Every endpoint, best-first: sticky current, then healthy ones
        by liveness score, then cooling ones by soonest expiry."""
        now = time.monotonic()  # graftlint: disable=naked-timer -- cooldown cadence clock, not a measured duration
        with self._lock:
            healthy, cooling = [], []
            for u in self._urls:
                (cooling if self._cool_until[u] > now else healthy).append(u)
            healthy.sort(key=lambda u: (u != self._current,
                                        tuple(-s for s in
                                              self._score.get(u, (0, 0.0)))))
            cooling.sort(key=lambda u: self._cool_until[u])
            return healthy + cooling

    def observe(self, url: str, head_version: int,
                heartbeat_age_s: float) -> None:
        """Record liveness evidence for ``url`` (from a ``/fleet/status``
        probe): higher head version wins, fresher heartbeats break
        ties."""
        with self._lock:
            self._score[str(url).rstrip("/")] = (
                int(head_version), -float(heartbeat_age_s))

    def report_success(self, url: str) -> None:
        with self._lock:
            self._failures[url] = 0
            self._cool_until[url] = 0.0
            if url != self._current:
                self._switches += 1
                telemetry.count("fleet/endpoint_switches")
                Log.info("fleet: endpoint failover -> %s", url)
            self._current = url

    def report_failure(self, url: str) -> None:
        now = time.monotonic()  # graftlint: disable=naked-timer -- cooldown cadence clock, not a measured duration
        with self._lock:
            n = self._failures.get(url, 0) + 1
            self._failures[url] = n
            cool = min(self._cool_max,
                       self._cool_base * (2.0 ** (n - 1)))
            self._cool_until[url] = now + cool
        telemetry.count("fleet/endpoint_failures")

    def state(self) -> Dict[str, Any]:
        now = time.monotonic()  # graftlint: disable=naked-timer -- cooldown cadence clock, not a measured duration
        with self._lock:
            return {
                "current": self._current,
                "switches": self._switches,
                "endpoints": {
                    u: {"failures": self._failures[u],
                        "cooling_s": round(max(
                            0.0, self._cool_until[u] - now), 3)}
                    for u in self._urls},
            }


class MultiEndpointStore:
    """Read-side store over SEVERAL fleet endpoints, duck-typing the
    replica-facing surface (``latest_publish``, ``latest_valid_publish``,
    ``load_model``, ``record_heartbeat``, ``state``) so
    :class:`~.replica.ReplicaWatcher` and ``bootstrap_model`` run
    UNCHANGED over a multi-homed region.

    Each call walks the selector's candidate order and returns the
    first endpoint's answer, reporting failures into the cooldown
    ranking as it goes; per-endpoint retries default to 1 so failover
    to the next endpoint happens within one poll, not after a full
    backoff ladder on the dead one. Correctness needs nothing more:
    publish version tokens are global, so the watcher's exactly-one-
    bump-per-publish invariant holds regardless of which endpoint
    served which poll. :meth:`probe` sweeps every endpoint's
    ``/fleet/status`` and feeds head-version + heartbeat-freshness
    evidence to the selector — the liveness ranking the heartbeat
    sidecars exist to enable.
    """

    def __init__(self, urls: Sequence[str], *,
                 timeout_s: float = 5.0,
                 retries: int = 1,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 jitter_seed: int = 0,
                 cooldown_base_s: float = 0.25,
                 cooldown_max_s: float = 8.0) -> None:
        self.selector = EndpointSelector(urls,
                                         cooldown_base_s=cooldown_base_s,
                                         cooldown_max_s=cooldown_max_s)
        self._stores: Dict[str, RemoteStore] = {}
        for i, url in enumerate(self.selector.urls):
            self._stores[url] = RemoteStore(
                url, timeout_s=timeout_s, retries=retries,
                backoff_base_s=backoff_base_s,
                backoff_max_s=backoff_max_s,
                # decorrelate the endpoints' jitter streams while
                # keeping the whole schedule a function of one seed
                jitter_seed=int(jitter_seed) + i)

    @property
    def base_url(self) -> str:
        """The sticky current endpoint (healthz/debug display)."""
        return self.selector.current()

    def _call(self, name: str, *args: Any, **kwargs: Any) -> Any:
        errors: List[str] = []
        for url in self.selector.candidates():
            store = self._stores[url]
            try:
                out = getattr(store, name)(*args, **kwargs)
            except TransportError as exc:
                self.selector.report_failure(url)
                errors.append("%s: %s" % (url, exc))
                continue
            self.selector.report_success(url)
            return out
        telemetry.count("fleet/all_endpoints_failed")
        raise TransportError(
            "%s failed on all %d fleet endpoint(s): %s"
            % (name, len(self._stores), "; ".join(errors)))

    # ----------------------------------------------------- store duck-typing
    def latest_publish(self) -> Optional[Dict[str, Any]]:
        return self._call("latest_publish")

    def latest_valid_publish(self, min_version: int = 0
                             ) -> Optional[Tuple[Dict[str, Any], str]]:
        return self._call("latest_valid_publish", min_version)

    def load_model(self, version: int) -> str:
        return self._call("load_model", version)

    def record_heartbeat(self, doc: Dict[str, Any]) -> bool:
        return self._call("record_heartbeat", doc)

    # ------------------------------------------------------------------ probe
    def probe(self) -> Dict[str, Any]:
        """Sweep every endpoint's ``/fleet/status`` once, feed the
        selector's liveness ranking, and return the per-endpoint view
        (reachable, head version, freshest heartbeat age) — also the
        evidence ``fleetctl`` renders."""
        out: Dict[str, Any] = {}
        for url in self.selector.urls:
            store = self._stores[url]
            try:
                doc = json.loads(store._request(_STATUS).decode("utf-8"))
            except (TransportError, _NotFound, ValueError):
                out[url] = {"reachable": False}
                continue
            head = int(doc.get("head_version", 0) or 0)
            ages = [float(n.get("age_s", 0.0))
                    for n in doc.get("nodes") or []
                    if isinstance(n, dict)]
            age = min(ages) if ages else float("inf")
            self.selector.observe(url, head, age)
            out[url] = {"reachable": True, "head_version": head,
                        "freshest_heartbeat_age_s":
                            (round(age, 3) if ages else None)}
        return out

    # ------------------------------------------------------------------ state
    def state(self) -> Dict[str, Any]:
        doc = {"selector": self.selector.state(),
               "endpoints": {u: s.state()
                             for u, s in self._stores.items()}}
        doc["base_url"] = self.selector.current()
        return doc


class IngestForwarder:
    """Relay labeled traffic to the node that can actually train on it.

    A replica (or a standby trainer on another box) has no online
    trainer to buffer ingest rows; before the control plane it answered
    409 and the rows were lost unless the client knew the trainer's
    address. The forwarder closes that gap: it resolves the current
    leader's serving URL — from the local store's lease record when the
    node hosts one (the lease advertises the holder's URL), otherwise
    by probing the configured fleet endpoints' ``/fleet/status`` — and
    re-POSTs the rows to the leader's ``/ingest/<model>``, stamping
    ``X-Fleet-Hops`` so a stale hint chain is bounded: a relay that
    arrives with ``hops >= max_hops`` is refused rather than forwarded
    again. A 409 answer carrying a ``leader_hint`` re-aims the relay
    once within the same hop budget. Resolution is cached briefly
    (``cache_ttl_s``) so a hot ingest path does not probe per chunk.
    """

    def __init__(self, *, store: Any = None,
                 urls: Sequence[str] = (),
                 timeout_s: float = 5.0,
                 max_hops: int = 3,
                 cache_ttl_s: float = 2.0) -> None:
        if store is None and not urls:
            raise LightGBMError(
                "IngestForwarder needs a local store or >= 1 fleet url")
        self._store = store
        self._urls = [str(u).rstrip("/") for u in urls]
        self._timeout = float(timeout_s)
        self._max_hops = max(1, int(max_hops))
        self._cache_ttl = float(cache_ttl_s)
        self._lock = threading.Lock()
        self._cached_leader: Optional[str] = None
        self._cached_at = 0.0
        self._forwarded_rows = 0
        self._forwarded = 0
        self._failed = 0

    @property
    def max_hops(self) -> int:
        return self._max_hops

    # ----------------------------------------------------------- leader lookup
    def _status_leader(self, url: str) -> Optional[str]:
        req = urllib.request.Request(url + _STATUS)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self._timeout) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
        except (OSError, ValueError):
            return None
        lease = doc.get("lease") if isinstance(doc, dict) else None
        if isinstance(lease, dict) and lease.get("held") \
                and lease.get("url"):
            return str(lease["url"]).rstrip("/")
        return None

    def leader_url(self) -> Optional[str]:
        """The current lease holder's advertised serving URL, or None
        when no live leader advertises one."""
        now = time.monotonic()  # graftlint: disable=naked-timer -- cache cadence clock, not a measured duration
        with self._lock:
            if (self._cached_leader is not None
                    and now - self._cached_at < self._cache_ttl):
                return self._cached_leader
        leader: Optional[str] = None
        if self._store is not None:
            try:
                lease = self._store.lease_state()
            except Exception:
                lease = {}
            if lease.get("held") and lease.get("url"):
                leader = str(lease["url"]).rstrip("/")
        if leader is None:
            for url in self._urls:
                leader = self._status_leader(url)
                if leader is not None:
                    break
        with self._lock:
            if leader is not None:
                self._cached_leader = leader
                self._cached_at = now
        return leader

    def invalidate(self) -> None:
        with self._lock:
            self._cached_leader = None

    # -------------------------------------------------------------- forwarding
    def forward(self, model_id: str, rows: Any, labels: Any,
                hops: int = 0) -> Dict[str, Any]:
        """Relay one labeled chunk to the leader's ``/ingest/<model>``.

        ``hops`` is the count already stamped on the INCOMING request;
        the outgoing relay carries ``hops + 1``. Raises
        :class:`TransportError` when the budget is exhausted, no leader
        is known, or the leader refuses — the HTTP handler maps it to
        503 (try again once a leader emerges)."""
        hops = int(hops)
        if hops >= self._max_hops:
            telemetry.count("fleet/forward_hop_limit")
            raise TransportError(
                "ingest relay exceeded %d hop(s) without reaching the "
                "lease holder (stale leader hints?)" % self._max_hops)
        leader = self.leader_url()
        if leader is None:
            with self._lock:
                self._failed += 1
            telemetry.count("fleet/forward_no_leader")
            raise TransportError(
                "no lease holder advertises a serving url; ingest "
                "cannot be forwarded")
        body = json.dumps({"rows": rows, "labels": labels},
                          sort_keys=True).encode("utf-8")
        n = len(labels) if hasattr(labels, "__len__") else 1
        attempted: List[str] = []
        while hops < self._max_hops:
            attempted.append(leader)
            req = urllib.request.Request(
                "%s/ingest/%s" % (leader, model_id), data=body,
                headers={"Content-Type": "application/json",
                         HOPS_HEADER: str(hops + 1)})
            try:
                with urllib.request.urlopen(
                        req, timeout=self._timeout) as resp:
                    doc = json.loads(resp.read().decode("utf-8"))
            except urllib.error.HTTPError as exc:
                try:
                    err = json.loads(exc.read().decode("utf-8"))
                except (ValueError, OSError):
                    err = {}
                hint = err.get("leader_hint") if isinstance(err, dict) \
                    else None
                if exc.code == 409 and hint \
                        and str(hint).rstrip("/") not in attempted:
                    # the node we relayed to is not the leader but knows
                    # (or thinks it knows) who is: re-aim within budget
                    self.invalidate()
                    leader = str(hint).rstrip("/")
                    hops += 1
                    continue
                with self._lock:
                    self._failed += 1
                telemetry.count("fleet/forward_errors")
                raise TransportError(
                    "ingest relay to %s refused: HTTP %d %s"
                    % (leader, exc.code, err.get("error")))
            except (OSError, ValueError) as exc:
                self.invalidate()
                with self._lock:
                    self._failed += 1
                telemetry.count("fleet/forward_errors")
                raise TransportError("ingest relay to %s failed: %s: %s"
                                     % (leader, type(exc).__name__, exc))
            with self._lock:
                self._forwarded += 1
                self._forwarded_rows += int(n)
            telemetry.count("fleet/forwarded_chunks")
            telemetry.count("fleet/forwarded_rows", int(n))
            doc = dict(doc) if isinstance(doc, dict) else {}
            doc["forwarded_to"] = leader
            return doc
        telemetry.count("fleet/forward_hop_limit")
        raise TransportError(
            "ingest relay exceeded %d hop(s) without reaching the "
            "lease holder (stale leader hints?)" % self._max_hops)

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {"forwarded_chunks": self._forwarded,
                    "forwarded_rows": self._forwarded_rows,
                    "failed": self._failed,
                    "cached_leader": self._cached_leader,
                    "max_hops": self._max_hops}
