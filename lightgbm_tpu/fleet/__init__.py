"""Fleet serving: durable online state + multi-replica model distribution.

The online/ subsystem (PR 8) trains, shadow-gates and hot-swaps models
under live load, but it is single-process and amnesiac. This package
adds the fleet layer on top of it:

- :class:`~lightgbm_tpu.fleet.store.FleetStore` — a durable JSONL store
  (the PR-10 ledger substrate: one-write appends, corrupt-line skip)
  holding the ingest stream, the promotion-gate history and
  version-tokened whole-model artifacts. A restarted trainer replays it
  and resumes its shadow window instead of cold-starting.
- :class:`~lightgbm_tpu.fleet.replica.ReplicaWatcher` — the
  multi-process story: one trainer process publishes promoted models
  through the store, N serving replicas watch it and hot-swap through
  the existing ``GBDT.adopt`` path, so every replica serves whole
  historical models only (one version bump per applied publish).

Per-tenant fairness (admission quotas + weighted-fair dequeue) lives in
:mod:`lightgbm_tpu.serve.batcher`; promotion hysteresis and the
auto-rollback live-metric watch live in
:mod:`lightgbm_tpu.online.trainer` — this package provides the
durability and distribution substrate they plug into.
"""
from .replica import ReplicaWatcher, bootstrap_model
from .store import FleetStore

__all__ = ["FleetStore", "ReplicaWatcher", "bootstrap_model"]
