"""Fleet serving: durable online state + multi-replica model distribution.

The online/ subsystem (PR 8) trains, shadow-gates and hot-swaps models
under live load, but it is single-process and amnesiac. This package
adds the fleet layer on top of it:

- :class:`~lightgbm_tpu.fleet.store.FleetStore` — a durable JSONL store
  (the PR-10 ledger substrate: one-write appends, corrupt-line skip)
  holding the ingest stream, the promotion-gate history and
  version-tokened whole-model artifacts. A restarted trainer replays it
  and resumes its shadow window instead of cold-starting.
- :class:`~lightgbm_tpu.fleet.replica.ReplicaWatcher` — the
  multi-process story: one trainer process publishes promoted models
  through the store, N serving replicas watch it and hot-swap through
  the existing ``GBDT.adopt`` path, so every replica serves whole
  historical models only (one version bump per applied publish).

Fleet hardening (PR 13) removes the layer's three single points of
failure: :class:`~lightgbm_tpu.fleet.store.FleetStore` grew a trainer
lease with epoch fencing (a standby trainer takes over a dead holder's
lease and a fenced-off zombie cannot publish), log compaction with
bit-identical replay, sha256-verified artifacts with
fall-back-to-previous-good, and orphan reaping;
:class:`~lightgbm_tpu.fleet.transport.RemoteStore` serves replicas that
do NOT share the trainer's filesystem (publish feed + artifacts over
stdlib HTTP with retries, capped deterministic-jitter backoff and
checksum verification); and :mod:`lightgbm_tpu.fleet.chaos` is the
seeded fault-injection switchboard the failover tests drive all of it
with.

The region-scale control plane (PR 20) removes the last shared-disk
assumption: :class:`~lightgbm_tpu.fleet.control.RemoteWriteStore` is
the WRITE surface over HTTP (remote lease ops, server-side fenced
publish with sha256-verified upload, ingest/gate appends, compaction),
:class:`~lightgbm_tpu.fleet.control.MultiEndpointStore` gives replicas
liveness-ranked multi-endpoint failover with capped cooldowns,
:class:`~lightgbm_tpu.fleet.control.IngestForwarder` relays labeled
traffic from any node to the lease holder (bounded leader-hint chain),
and snapshot compaction (``FleetStore.compact(snapshot_rows=...)``)
lets a cold standby bootstrap from one snapshot blob + log tail
instead of a full replay.

Per-tenant fairness (admission quotas + weighted-fair dequeue) lives in
:mod:`lightgbm_tpu.serve.batcher`; promotion hysteresis and the
auto-rollback live-metric watch live in
:mod:`lightgbm_tpu.online.trainer` — this package provides the
durability and distribution substrate they plug into.
"""
from .control import (EndpointSelector, IngestForwarder,
                      MultiEndpointStore, RemoteWriteStore)
from .replica import ReplicaWatcher, bootstrap_model
from .store import (CorruptArtifactError, FleetStore, StaleLeaseError)
from .transport import RemoteStore, TransportError

__all__ = ["FleetStore", "ReplicaWatcher", "RemoteStore",
           "RemoteWriteStore", "MultiEndpointStore", "EndpointSelector",
           "IngestForwarder", "bootstrap_model", "StaleLeaseError",
           "CorruptArtifactError", "TransportError"]
