"""Network distribution for the fleet store: replicas off the trainer's
filesystem.

The trainer-side :class:`~lightgbm_tpu.serve.http.PredictServer` (when
given a ``fleet_store``) serves the store's publish feed and artifacts
over the existing stdlib HTTP stack:

    GET /fleet/latest             newest valid publish event (404: none)
    GET /fleet/publishes          {"publishes": [events oldest-first]}
    GET /fleet/artifact/<version> raw whole-model artifact bytes

:class:`RemoteStore` is the client half: it duck-types the three store
methods :class:`~lightgbm_tpu.fleet.replica.ReplicaWatcher` and
``bootstrap_model`` use (``latest_publish``, ``latest_valid_publish``,
``load_model``), so a replica pointed at a URL runs the identical
watcher code as one on the shared filesystem. The version-token
protocol already tolerates an unreliable transport — replicas converge
by applying the newest token whenever they can next reach the feed —
so the client only needs timeouts, capped exponential backoff with
deterministic jitter (seeded, so chaos tests reproduce byte-identical
schedules), and sha256 verification of every downloaded artifact
against its publish event: a partition stalls convergence, never
corrupts it, and resume needs no extra state.
"""
from __future__ import annotations

import http.client
import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple

from ..obs import telemetry
from ..obs_trace import TRACE_HEADER, format_trace_id, tracer
from ..utils.log import LightGBMError, Log
from . import chaos
from .store import CorruptArtifactError, _verify_artifact

_LATEST = "/fleet/latest"
_PUBLISHES = "/fleet/publishes"
_ARTIFACT = "/fleet/artifact/%d"
_HEARTBEAT = "/fleet/heartbeat"


class TransportError(LightGBMError):
    """A /fleet request failed every retry (store unreachable)."""


class _NotFound(Exception):
    """Internal: the remote answered 404 (a meaning, not a failure)."""


class _Rejected(Exception):
    """Internal: the remote answered a status listed in ``no_retry`` —
    a protocol verdict (fence 409, bad upload 400), not an outage.
    Carries the code and decoded body so the caller can read the
    verdict's payload (e.g. a ``leader_hint``)."""

    def __init__(self, code: int, body: bytes) -> None:
        super().__init__("HTTP %d" % code)
        self.code = int(code)
        self.body = body

    def doc(self) -> Dict[str, Any]:
        try:
            doc = json.loads(self.body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return {}
        return doc if isinstance(doc, dict) else {}


class RemoteStore:
    """Read-only fleet store over HTTP, duck-typing ``FleetStore``'s
    replica-facing surface.

    Every request gets ``retries`` attempts with capped exponential
    backoff; the jitter factor is drawn from a ``jitter_seed``ed RNG so
    two runs with the same seed back off identically (no wall-clock
    flake in the chaos tests). Artifact bytes are verified against the
    publish event's sha256 + length — a torn or tampered download is
    counted (``fleet/transport_checksum_failures``) and the previous
    good publish is used instead, exactly like a corrupt local artifact.
    """

    def __init__(self, base_url: str, *,
                 timeout_s: float = 5.0,
                 retries: int = 4,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 jitter_seed: int = 0) -> None:
        base_url = str(base_url).rstrip("/")
        if not base_url.startswith(("http://", "https://")):
            raise LightGBMError("fleet url must be http(s)://..., got %r"
                                % base_url)
        if timeout_s <= 0:
            raise LightGBMError("fleet timeout_s must be > 0, got %g"
                                % timeout_s)
        self._base = base_url
        self._timeout = float(timeout_s)
        self._retries = max(0, int(retries))
        self._backoff_base = float(backoff_base_s)
        self._backoff_max = float(backoff_max_s)
        # guards the retry counters and the jitter RNG (poller thread +
        # boot-time bootstrap + /healthz state reads)
        self._lock = threading.Lock()
        self._rng = random.Random(int(jitter_seed))
        self._requests = 0
        self._retried = 0
        self._errors = 0
        self._checksum_failures = 0
        self._heartbeats_sent = 0
        self._last_error = ""
        self._corrupt_seen: set = set()

    @property
    def base_url(self) -> str:
        return self._base

    # --------------------------------------------------------------- requests
    def _sleep_s(self, attempt: int) -> float:
        """Deterministic-jitter capped exponential backoff for retry
        ``attempt`` (0-based): base·2^attempt capped, scaled by a seeded
        factor in [0.5, 1.0]."""
        with self._lock:
            factor = 0.5 + 0.5 * self._rng.random()
        return min(self._backoff_max,
                   self._backoff_base * (2.0 ** attempt)) * factor

    def _request(self, path: str, data: Optional[bytes] = None,
                 no_retry: Tuple[int, ...] = ()) -> bytes:
        """GET ``path`` (POST when ``data`` is given) with retries.
        Raises :class:`_NotFound` on 404 (no retry — absence is an
        answer), :class:`_Rejected` for statuses in ``no_retry`` (a
        protocol verdict — retrying a fence rejection would just hammer
        the new leader's 409), and :class:`TransportError` once every
        attempt failed.

        The active span's trace id (if any) rides along as
        ``X-Trace-Id`` so the trainer-side handler can join its serve
        spans to the replica's poll trace."""
        last: Optional[BaseException] = None
        headers = {}
        trace_id = tracer.current_trace_id()
        if trace_id is not None:
            headers[TRACE_HEADER] = format_trace_id(trace_id)
        if data is not None:
            headers["Content-Type"] = "application/json"
        for attempt in range(self._retries + 1):
            if attempt > 0:
                with self._lock:
                    self._retried += 1
                telemetry.count("fleet/transport_retries")
                delay = self._sleep_s(attempt - 1)
                telemetry.gauge("fleet/transport_backoff_ms",
                                delay * 1000.0)
                time.sleep(delay)
            with self._lock:
                self._requests += 1
            telemetry.count("fleet/transport_requests")
            try:
                act = chaos.hit("transport/request")
                req = urllib.request.Request(self._base + path, data=data,
                                             headers=headers)
                with urllib.request.urlopen(req,
                                            timeout=self._timeout) as resp:
                    body = resp.read()
                if act is not None and act[0] == "torn":
                    body = body[:int(len(body) * float(act[1]))]
                return body
            except urllib.error.HTTPError as exc:
                if exc.code == 404:
                    raise _NotFound(path)
                if exc.code in no_retry:
                    raise _Rejected(exc.code, exc.read() or b"")
                last = exc  # 5xx/4xx: retry — the server may be mid-restart
            except (OSError, http.client.HTTPException,
                    chaos.InjectedFault) as exc:
                last = exc  # refused/reset/timeout/short read/injected drop
        with self._lock:
            self._errors += 1
            self._last_error = "%s: %s" % (type(last).__name__, last)
        telemetry.count("fleet/transport_errors")
        raise TransportError("%s %s%s failed after %d attempt(s): %s: %s"
                             % ("POST" if data is not None else "GET",
                                self._base, path, self._retries + 1,
                                type(last).__name__, last))

    # ------------------------------------------------------- store duck-typing
    def latest_publish(self) -> Optional[Dict[str, Any]]:
        try:
            doc = json.loads(self._request(_LATEST).decode("utf-8"))
        except _NotFound:
            return None
        return doc if isinstance(doc, dict) else None

    def load_model(self, version: int) -> str:
        """Raw artifact fetch, no checksum (prefer
        :meth:`latest_valid_publish`)."""
        try:
            return self._request(_ARTIFACT % int(version)).decode("utf-8")
        except _NotFound:
            raise CorruptArtifactError("remote artifact v%d not found"
                                       % int(version))

    def latest_valid_publish(self, min_version: int = 0
                             ) -> Optional[Tuple[Dict[str, Any], str]]:
        """Newest publish past ``min_version`` whose downloaded artifact
        verifies, walking back past torn/corrupt/missing downloads —
        the same fallback contract as the filesystem store."""
        try:
            doc = json.loads(self._request(_PUBLISHES).decode("utf-8"))
        except _NotFound:
            return None
        pubs = doc.get("publishes") if isinstance(doc, dict) else None
        for e in reversed(pubs or []):
            version = int(e.get("version", 0))
            if version <= int(min_version):
                break
            try:
                data = self._request(_ARTIFACT % version)
                _verify_artifact(e, data)
                return e, data.decode("utf-8")
            except (_NotFound, CorruptArtifactError,
                    UnicodeDecodeError) as exc:
                with self._lock:
                    seen = version in self._corrupt_seen
                    self._corrupt_seen.add(version)
                    self._checksum_failures += 1
                telemetry.count("fleet/transport_checksum_failures")
                if not seen:
                    telemetry.count("fleet/corrupt_artifacts")
                    Log.warning("fleet: remote artifact v%d rejected "
                                "(%s: %s); falling back", version,
                                type(exc).__name__, exc)
        return None

    def record_heartbeat(self, doc: Dict[str, Any]) -> bool:
        """POST a node heartbeat to the trainer's ``/fleet/heartbeat``.

        Duck-types :meth:`FleetStore.record_heartbeat` so remote
        replicas federate into the same ``/fleet/status`` rollup as
        shared-filesystem nodes. Returns False (without retrying the
        whole backoff ladder into an error) when the trainer predates
        the endpoint (404) — heartbeats are observability, not state."""
        body = json.dumps(doc, sort_keys=True).encode("utf-8")
        try:
            self._request(_HEARTBEAT, data=body)
        except _NotFound:
            return False
        with self._lock:
            self._heartbeats_sent += 1
        telemetry.count("fleet/heartbeats_sent")
        return True

    # ------------------------------------------------------------------ state
    def state(self) -> Dict[str, Any]:
        """JSON-serializable transport summary (surfaced on /healthz)."""
        with self._lock:
            return {
                "base_url": self._base,
                "requests": self._requests,
                "retries": self._retried,
                "errors": self._errors,
                "checksum_failures": self._checksum_failures,
                "heartbeats_sent": self._heartbeats_sent,
                "last_error": self._last_error,
                "timeout_s": self._timeout,
            }
