"""Durable fleet state: one directory per served model.

    <root>/<model_id>/events.jsonl      append-only event log
    <root>/<model_id>/models/v%06d.txt  immutable whole-model artifacts
    <root>/<model_id>/lease.json        trainer lease (holder + epoch)

The event log rides the PR-10 ledger substrate
(:func:`~lightgbm_tpu.obs_ledger.append_jsonl` /
:func:`~lightgbm_tpu.obs_ledger.read_jsonl`): every append is ONE write
call of one JSON line, so concurrent writers (HTTP ingest handlers, the
trainer worker) interleave whole lines and a SIGKILL mid-append leaves at
most one partial line, skipped on read. Event kinds:

- ``ingest``: one labeled traffic chunk (rows + labels). Replayed on
  boot so a restarted server resumes its shadow window and training
  buffer instead of cold-starting.
- ``gate``: one promotion-gate cycle (result, consecutive-win count for
  promotion hysteresis, the consumed-row watermark separating
  already-trained traffic from still-buffered traffic).
- ``publish``: a whole model became servable under a monotonically
  increasing **version token**. The artifact is written to a temp file
  and ``os.replace``d into place BEFORE the event lands, so a replica
  that sees the event always reads a complete model — whole historical
  models only, never a torn artifact. The event records the artifact's
  ``sha256`` + byte length (verified on load) and the publisher's
  ``lease_epoch`` (zombie fencing, below).
- ``compact``: a snapshot record (watermark, win streak, row base,
  version/epoch floors) standing in for every event truncated before it
  — replay from a compacted log is bit-identical to the full log.

Rollbacks are publishes too (``event="rollback"``): replicas converge by
always applying the newest version token, so a rollback distributes
exactly like a promotion.

**Failover.** Exactly one trainer may publish at a time. The lease file
holds ``{holder, epoch, expires_ts}`` and is swapped atomically
(``os.replace``); every acquisition — takeover OR re-acquisition —
bumps ``epoch``, the fencing token. A trainer arms its store with
:meth:`set_fence`; :meth:`publish` then re-reads the lease and refuses
(:class:`StaleLeaseError`) unless holder+epoch still match, so a paused
("zombie") trainer that lost its lease cannot publish over its
successor. Readers additionally reject any publish event whose non-zero
epoch is below an epoch already seen earlier in the log (a zombie write
that raced the fence check on another host). Epoch 0 marks an UNFENCED
publisher (leasing disabled) and is exempt from that rejection —
turning ``fleet_lease_ttl_s`` off after a fenced tenure must not
silently drop every later publish (it is warned about and counted
instead).

**Cross-process writes.** The failover feature makes the log genuinely
multi-writer: a standby trainer persists every ingest chunk to the same
``events.jsonl`` the active holder appends to. Single appends interleave
safely (one write call per line), but compaction's snapshot→rewrite and
the open-time torn-tail repair do not — so every append, the repair and
the whole compaction critical section hold a cross-process writer mutex
(``flock`` on the ``events.jsonl.lock`` sidecar, released by the kernel
if the holder dies). Replica-role opens pass ``read_only=True`` and
never mutate the log at all.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

try:
    import fcntl   # POSIX: cross-process writer mutex via flock
except ImportError:   # pragma: no cover — non-POSIX fallback below
    fcntl = None

from .. import obs
from ..obs import telemetry
from ..obs_ledger import append_jsonl, read_jsonl
from ..utils.log import LightGBMError, Log
from . import chaos

#: schema version stamped on every event; readers skip newer majors
STORE_VERSION = 1

#: publish-event reasons (reporting only — replicas apply them all)
PUBLISH_EVENTS = ("boot", "promotion", "rollback")

_ARTIFACT_FMT = "v%06d.txt"
_SNAPSHOT_FMT = "s%06d.json"

#: a lease-acquisition guard file older than this is a crashed acquirer
_GUARD_STALE_S = 5.0


def _verify_blob(what: str, want_sha: Optional[str], want_bytes: int,
                 data: bytes) -> None:
    """sha256 + byte-length check shared by model artifacts and buffer
    snapshots (and the HTTP transport's downloaded copies of both).
    ``want_sha`` None passes (records from before checksums). Raises
    :class:`CorruptArtifactError` on mismatch."""
    if want_sha is None:
        return
    if want_bytes >= 0 and len(data) != want_bytes:
        raise CorruptArtifactError(
            "%s truncated: %d bytes, event says %d"
            % (what, len(data), want_bytes))
    got = hashlib.sha256(data).hexdigest()
    if got != want_sha:
        raise CorruptArtifactError(
            "%s sha256 mismatch: %s != %s" % (what, got, want_sha))


def _verify_artifact(event: Dict[str, Any], data: bytes) -> None:
    """Check artifact ``data`` against its publish event's sha256 + byte
    length. Events from before checksums carry no ``sha256`` and pass.
    Raises :class:`CorruptArtifactError` on mismatch."""
    _verify_blob("artifact v%d" % int(event.get("version", 0)),
                 event.get("sha256"), int(event.get("bytes", -1)), data)


def _verify_snapshot(record: Dict[str, Any], data: bytes) -> None:
    """Check snapshot ``data`` against its compact record's ``snapshot``
    section (shared with the HTTP transport's downloaded copies)."""
    snap = record.get("snapshot") or {}
    _verify_blob("snapshot s%06d" % int(snap.get("id", 0)),
                 snap.get("sha256"), int(snap.get("bytes", -1)), data)


class StaleLeaseError(LightGBMError):
    """A fenced publish was refused: the store's lease is no longer held
    by this trainer at this epoch (another trainer took over)."""


class CorruptArtifactError(LightGBMError):
    """A model artifact failed its publish-event sha256/length check."""


class FleetStore:
    """Durable event log + model-artifact directory for one served model.

    Thread-safe: appends arrive from HTTP handler threads (ingest) and
    the trainer worker (gate/publish); reads come from replica-watcher
    threads and boot-time replay. The in-memory counters exist only for
    cheap ``state()`` snapshots — the file is the source of truth.

    ``orphan_grace_s``: on open, artifact files newer than every publish
    event (a publisher died between ``os.replace`` and its event append)
    are reaped — but only when older than this grace, so opening a store
    never races another process's in-flight publish.

    ``read_only``: a replica-role open over a shared filesystem. Skips
    the destructive open-time maintenance (torn-tail repair, orphan
    reaping) a pure reader must never run against a live writer's files.
    """

    def __init__(self, root: str, model_id: str = "default", *,
                 orphan_grace_s: float = 60.0,
                 read_only: bool = False) -> None:
        model_id = str(model_id)
        if not model_id or "/" in model_id or model_id.startswith("."):
            raise LightGBMError("fleet model_id must be a plain name, "
                                "got %r" % model_id)
        self._root = os.path.abspath(root)
        self._model_id = model_id
        self._dir = os.path.join(self._root, model_id)
        self._events_path = os.path.join(self._dir, "events.jsonl")
        self._models_dir = os.path.join(self._dir, "models")
        self._lease_path = os.path.join(self._dir, "lease.json")
        self._heartbeats_dir = os.path.join(self._dir, "heartbeats")
        self._snapshots_dir = os.path.join(self._dir, "snapshots")
        os.makedirs(self._models_dir, exist_ok=True)
        # guards version allocation, the fence, compaction's rewrite and
        # the state counters; re-entrant because publish/compact append
        # through the same locked _append as the HTTP ingest path
        self._lock = threading.RLock()
        self._fence: Optional[Tuple[str, int]] = None
        self._ingest_rows = 0
        self._publishes = 0
        self._compactions = 0
        self._last_compact_ts = 0.0
        self._orphans_reaped = 0
        self._stale_seen: set = set()
        self._corrupt_seen: set = set()
        self._warned_unfenced = False
        self._read_only = bool(read_only)
        if not self._read_only:
            # under the writer mutex: a tail that is torn while no other
            # writer can be mid-append is genuinely dead, never a
            # partially-visible in-flight line of a live process
            with self._writer_mutex():
                self._repair_torn_tail()
        valid, max_version, _max_epoch, _stale = self._scan_publishes()
        self._last_version = max_version
        if not self._read_only:
            self._reap_orphans(max_version, float(orphan_grace_s))

    # ---------------------------------------------------------------- identity
    @property
    def root(self) -> str:
        return self._root

    @property
    def model_id(self) -> str:
        return self._model_id

    @property
    def events_path(self) -> str:
        return self._events_path

    def log_bytes(self) -> int:
        try:
            return os.path.getsize(self._events_path)
        except OSError:
            return 0

    # ----------------------------------------------------------------- append
    def _stamp(self, kind: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        entry = {"v": STORE_VERSION, "kind": kind,
                 "ts": time.time()}  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        entry.update(payload)
        return entry

    def _repair_torn_tail(self) -> None:
        """Truncate a partial final line (a writer SIGKILLed mid-append).
        Readers already skip it, but without the truncation the NEXT
        append would glue onto the torn prefix and both lines would read
        back as one corrupt line — a restarted trainer's first event
        silently lost. Runs once, on open."""
        try:
            size = os.path.getsize(self._events_path)
        except OSError:
            return
        if size == 0:
            return
        with open(self._events_path, "rb+") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) == b"\n":
                return
            # walk back block-wise to the last complete line's newline
            pos, keep = size, 0
            while pos > 0:
                step = min(4096, pos)
                pos -= step
                f.seek(pos)
                idx = f.read(step).rfind(b"\n")
                if idx >= 0:
                    keep = pos + idx + 1
                    break
            f.truncate(keep)
        telemetry.count("fleet/torn_tail_repaired")
        Log.warning("fleet: truncated %d-byte torn tail line in %s",
                    size - keep, self._events_path)

    @contextmanager
    def _writer_mutex(self):
        """Cross-process mutex over every ``events.jsonl`` mutation.

        The in-process RLock cannot serialize a standby trainer's ingest
        appends (another process, its own store instance) against this
        process's compaction rewrite — a line appended between the scan
        and the ``os.replace`` would die with the old inode. So every
        append, the open-time torn-tail repair and the whole compaction
        critical section hold an exclusive ``flock`` on the
        ``events.jsonl.lock`` sidecar: it blocks until free and the
        kernel releases it when the holder dies, so there is no stale
        state to break. Non-POSIX fallback: the lease-style O_EXCL
        guard, best-effort (proceeds with a warning if never acquired).
        """
        path = self._events_path + ".lock"
        if fcntl is not None:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
                yield
            finally:
                os.close(fd)   # closing the fd drops the flock
            return
        held = self._guard_wait(path,   # pragma: no cover — non-POSIX
                                timeout_s=2.0 * _GUARD_STALE_S)
        if not held:   # pragma: no cover
            Log.warning("fleet: events writer guard %s stuck busy; "
                        "proceeding unserialized", path)
            yield
            return
        try:   # pragma: no cover
            yield
        finally:
            self._guard_release(path)

    def _assert_writable(self) -> None:
        if self._read_only:
            raise LightGBMError(
                "fleet store %s opened read_only (replica role) cannot "
                "append, publish or compact" % self._dir)

    def _append(self, entry: Dict[str, Any]) -> None:
        """All event appends funnel here: serialized against compaction's
        atomic rewrite (in-process by the store lock, cross-process by
        the events writer mutex), and carrying the ``store/append`` chaos
        point (a torn action writes a prefix of the line and raises — the
        simulated crash the corrupt-line skip on replay recovers from; a
        reorder action parks the line so it lands right AFTER the next
        append — the delayed-write-past-its-successor race replay's
        log-order row offsets must stay consistent under)."""
        self._assert_writable()
        with self._lock, self._writer_mutex():
            act = chaos.hit("store/append")
            if act is not None and act[0] == "torn":
                line = (json.dumps(entry, sort_keys=True)
                        + "\n").encode("utf-8")
                cut = max(1, int(len(line) * float(act[1])))
                with open(self._events_path, "ab") as f:
                    f.write(line[:cut])
                raise chaos.InjectedFault(
                    "torn append (%d/%d bytes) at %s"
                    % (cut, len(line), entry.get("kind")))
            plan = chaos.active()
            if act is not None and act[0] == "reorder" and plan is not None:
                plan.park("store/append", entry)
                return
            append_jsonl(self._events_path, entry)
            if plan is not None:
                for parked in plan.take_parked("store/append"):
                    append_jsonl(self._events_path, parked)

    def append_ingest(self, X, y) -> None:
        """Persist one labeled traffic chunk (one JSONL line). Called on
        the ingest path BEFORE the in-memory buffer push, so a crash
        after the append replays the chunk instead of losing it."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        y = np.asarray(y, np.float64).ravel()
        self._append(self._stamp("ingest", {
            "n": int(len(y)), "rows": X.tolist(), "labels": y.tolist()}))
        with self._lock:
            self._ingest_rows += int(len(y))
        telemetry.count("fleet/ingest_rows_persisted", int(len(y)))

    def append_gate(self, result: str, wins: int, consumed_rows: int,
                    losses: Optional[Dict[str, float]] = None) -> None:
        """Persist one promotion-gate cycle: its verdict, the
        consecutive-win counter (promotion-hysteresis state a restarted
        trainer must resume), and the consumed-row watermark (rows
        ingested before it are already trained — replay keeps them out
        of the training buffer but in the shadow window)."""
        self._append(self._stamp("gate", {
            "result": str(result), "wins": int(wins),
            "consumed_rows": int(consumed_rows),
            "losses": losses}))

    # ------------------------------------------------------------------ lease
    def _read_lease(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self._lease_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def _write_lease(self, doc: Dict[str, Any]) -> None:
        chaos.hit("store/lease")
        tmp = self._lease_path + ".tmp.%d" % os.getpid()
        data = json.dumps(doc, sort_keys=True).encode("utf-8")
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            done = 0
            while done < len(data):
                done += os.write(fd, data[done:])
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self._lease_path)

    def _guard_acquire(self, path: str) -> bool:
        """O_EXCL guard file serializing a read-modify-write across
        processes; a guard left by a crashed acquirer is broken after
        ``_GUARD_STALE_S``. Returns False when another acquirer is live
        right now (the caller treats that as guard-unavailable)."""
        for _ in range(2):
            try:
                fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                             0o644)
            except FileExistsError:
                try:
                    age = time.time() - os.path.getmtime(path)  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
                except OSError:
                    continue
                if age > _GUARD_STALE_S:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                return False
            os.write(fd, b"%d" % os.getpid())
            os.close(fd)
            return True
        return False

    def _guard_release(self, path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def _guard_wait(self, path: str, timeout_s: float = 0.5) -> bool:
        """Blocking :meth:`_guard_acquire`: the guard's critical sections
        are a tiny json read+write, so a busy guard clears in
        microseconds — spin briefly instead of failing a heartbeat (and
        demoting a healthy trainer) over a concurrent standby's probe."""
        deadline = obs.monotonic() + float(timeout_s)
        while True:
            if self._guard_acquire(path):
                return True
            if obs.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def acquire_lease(self, holder: str, ttl_s: float,
                      url: Optional[str] = None) -> Optional[int]:
        """Try to take the trainer lease. Returns the new fencing epoch,
        or None while another live holder has it. EVERY successful
        acquisition — takeover of an expired lease, or re-acquisition by
        the same holder — bumps the epoch, so an epoch uniquely names
        one continuous tenure.

        ``url`` advertises the holder's serving endpoint in the lease
        record: it is the ``leader_hint`` the control plane hands to
        nodes whose labeled traffic must be forwarded to whoever can
        actually train on it."""
        holder = str(holder)
        if ttl_s <= 0:
            raise LightGBMError("lease ttl_s must be > 0, got %g" % ttl_s)
        with self._lock:
            if not self._guard_acquire(self._lease_path + ".lock"):
                return None
            try:
                cur = self._read_lease()
                now = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
                if (cur is not None and cur.get("holder") != holder
                        and float(cur.get("expires_ts", 0.0)) > now):
                    return None
                epoch = int(cur.get("epoch", 0)) + 1 if cur else 1
                doc = {
                    "v": STORE_VERSION, "holder": holder, "epoch": epoch,
                    "expires_ts": now + float(ttl_s), "acquired_ts": now,
                    "pid": os.getpid()}
                if url:
                    doc["url"] = str(url)
                self._write_lease(doc)
            finally:
                self._guard_release(self._lease_path + ".lock")
        telemetry.count("fleet/lease_acquired")
        telemetry.gauge("fleet/lease_epoch", epoch)
        Log.info("fleet: %s acquired trainer lease (epoch %d, ttl %gs)",
                 holder, epoch, ttl_s)
        return epoch

    def renew_lease(self, holder: str, epoch: int, ttl_s: float,
                    url: Optional[str] = None) -> bool:
        """Heartbeat: extend the lease iff still held by ``holder`` at
        ``epoch``. An expired-but-untaken lease renews fine (the holder
        merely heartbeat late); a lease re-acquired by anyone (epoch
        moved on) does not — the caller must demote to standby.

        Runs inside the same O_EXCL guard as :meth:`acquire_lease`:
        without it, an old holder's renew racing a standby's takeover
        could read the pre-takeover lease and write it back (extended,
        old epoch) AFTER the takeover's ``os.replace``, resurrecting the
        dead epoch and flapping both trainers active/standby."""
        lock = self._lease_path + ".lock"
        with self._lock:
            if not self._guard_wait(lock):
                Log.warning("fleet: lease renewal for %s blocked by a "
                            "concurrent acquirer; demoting", holder)
                return False
            try:
                cur = self._read_lease()
                if (cur is None or cur.get("holder") != str(holder)
                        or int(cur.get("epoch", -1)) != int(epoch)):
                    return False
                now = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
                cur["expires_ts"] = now + float(ttl_s)
                if url:
                    # a holder that learned its bound address after the
                    # acquisition (ephemeral port) advertises it here
                    cur["url"] = str(url)
                self._write_lease(cur)
            finally:
                self._guard_release(lock)
        return True

    def release_lease(self, holder: str, epoch: int) -> bool:
        """Clean handoff: expire the lease immediately (epoch kept, so
        the next acquirer still bumps past it). No-op unless still held
        by ``holder`` at ``epoch``. Guarded like :meth:`renew_lease` —
        an unguarded release racing a takeover could clobber the new
        holder's lease with an expired copy of the old one."""
        lock = self._lease_path + ".lock"
        with self._lock:
            if not self._guard_wait(lock):
                Log.warning("fleet: lease release for %s blocked by a "
                            "concurrent acquirer; leaving it to expire",
                            holder)
                return False
            try:
                cur = self._read_lease()
                if (cur is None or cur.get("holder") != str(holder)
                        or int(cur.get("epoch", -1)) != int(epoch)):
                    return False
                cur["expires_ts"] = 0.0
                cur["released_ts"] = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
                self._write_lease(cur)
            finally:
                self._guard_release(lock)
        return True

    def lease_state(self) -> Dict[str, Any]:
        """JSON-serializable lease summary (surfaced on /healthz)."""
        cur = self._read_lease()
        if cur is None:
            return {"held": False, "holder": None, "epoch": 0,
                    "expires_ts": 0.0, "url": None}
        expires = float(cur.get("expires_ts", 0.0))
        return {
            "held": expires > time.time(),  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
            "holder": cur.get("holder"),
            "epoch": int(cur.get("epoch", 0)),
            "expires_ts": expires,
            "url": cur.get("url"),
        }

    def set_fence(self, holder: str, epoch: int) -> None:
        """Arm publish fencing: every later :meth:`publish` re-checks the
        lease against this (holder, epoch) and stamps the epoch into the
        publish event."""
        with self._lock:
            self._fence = (str(holder), int(epoch))

    def clear_fence(self) -> None:
        with self._lock:
            self._fence = None

    # ---------------------------------------------------------------- publish
    def publish(self, model_str: str, event: str = "promotion",
                meta: Optional[Dict[str, Any]] = None, *,
                fence: Optional[Tuple[str, int]] = None) -> int:
        """Publish one whole model under the next version token.

        The artifact is written to a temp path and ``os.replace``d (atomic
        on POSIX) before the publish event is appended — a watcher that
        sees the event can always read the complete artifact. The event
        carries the artifact's sha256 + byte length (verified by
        :meth:`load_publish`) and the publisher's fencing epoch. When a
        fence is armed and the lease moved on, raises
        :class:`StaleLeaseError` BEFORE anything is written. Returns the
        allocated version token.

        ``fence`` is a per-call (holder, epoch) override for publishes
        relayed on behalf of a REMOTE trainer (``POST /fleet/publish``):
        the remote writer's claimed identity is checked against the
        lease exactly like the local fence, without touching whatever
        fence this process's own trainer armed via :meth:`set_fence`.
        Epoch <= 0 in the override means an unfenced remote publisher
        (same contract as local epoch-0 publishes)."""
        if event not in PUBLISH_EVENTS:
            raise LightGBMError("publish event must be one of %s, got %r"
                                % ("|".join(PUBLISH_EVENTS), event))
        self._assert_writable()
        with self._lock:
            eff_fence = self._fence
            if fence is not None:
                eff_fence = ((str(fence[0]), int(fence[1]))
                             if int(fence[1]) > 0 else None)
            epoch = 0
            if eff_fence is not None:
                lease = self._read_lease()
                if (lease is None
                        or lease.get("holder") != eff_fence[0]
                        or int(lease.get("epoch", -1)) != eff_fence[1]):
                    telemetry.count("fleet/stale_publishes_blocked")
                    raise StaleLeaseError(
                        "publish fenced off: lease now %r, this publisher "
                        "held %r" % (lease, eff_fence))
                epoch = eff_fence[1]
            # a previous active trainer (another process, another store
            # instance over the same dir) may have published since this
            # store was opened: re-read the allocation floor from the log
            # so a standby that takes over never reuses a version token
            _valid, max_version, max_epoch, _stale = self._scan_publishes()
            if max_version > self._last_version:
                self._last_version = max_version
            if epoch == 0 and max_epoch > 0:
                # unfenced publish into a log with fenced history:
                # leasing was on once and is off now — readers apply the
                # publish (epoch 0 is exempt from stale rejection) but
                # the likely misconfiguration must be loud
                telemetry.count("fleet/unfenced_publishes")
                if not self._warned_unfenced:
                    self._warned_unfenced = True
                    Log.warning(
                        "fleet: unfenced publish (lease epoch 0) into a "
                        "store whose log has fenced publishes up to "
                        "epoch %d — was fleet_lease_ttl_s disabled on "
                        "purpose?", max_epoch)
            version = self._last_version + 1
            name = _ARTIFACT_FMT % version
            final = os.path.join(self._models_dir, name)
            tmp = final + ".tmp.%d" % os.getpid()
            data = model_str.encode("utf-8")
            view = memoryview(data)
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                done = 0
                while done < len(view):
                    done += os.write(fd, view[done:])
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, final)
            # the crash-between-replace-and-event window orphan reaping
            # covers; a ("raise",...) action here leaves exactly that
            chaos.hit("store/publish")
            self._append(self._stamp("publish", {
                "version": version, "artifact": name, "event": event,
                "sha256": hashlib.sha256(data).hexdigest(),
                "bytes": len(data), "lease_epoch": epoch,
                "meta": dict(meta) if meta else None}))
            self._last_version = version
            self._publishes += 1
        telemetry.count("fleet/publishes")
        telemetry.gauge("fleet/published_version", version)
        telemetry.gauge("fleet/events_log_bytes", self.log_bytes())
        return version

    # ------------------------------------------------------------------ read
    def events(self, kind: Optional[str] = None) -> Iterator[Dict[str, Any]]:
        """Events oldest-first (corrupt/partial lines skipped)."""
        for e in read_jsonl(self._events_path, max_version=STORE_VERSION):
            if kind is None or e.get("kind") == kind:
                yield e

    def _scan_publishes(self) -> Tuple[List[Dict[str, Any]], int, int,
                                       List[Dict[str, Any]]]:
        """One pass over the log → (valid publishes in append order,
        max version over ALL publishes incl. stale + compact floor,
        max epoch, stale publishes).

        A publish is STALE when its NON-ZERO lease epoch is below an
        epoch already seen earlier in the log — a zombie trainer's write
        that raced the fence. Epoch 0 marks an unfenced publisher
        (leasing disabled) and is exempt: an operator turning
        ``fleet_lease_ttl_s`` off after a fenced tenure must not have
        every later publish silently dropped forever. Stale versions
        still raise the allocation floor (tokens are never reused) but
        are never applied. Compact records carry the floors for
        everything they truncated."""
        valid: List[Dict[str, Any]] = []
        stale: List[Dict[str, Any]] = []
        max_version = 0
        max_epoch = 0
        for e in self.events():
            kind = e.get("kind")
            if kind == "compact":
                max_version = max(max_version,
                                  int(e.get("last_version", 0)))
                max_epoch = max(max_epoch, int(e.get("lease_epoch", 0)))
                continue
            if kind != "publish":
                continue
            v = e.get("version")
            if not isinstance(v, int):
                continue
            max_version = max(max_version, v)
            epoch = int(e.get("lease_epoch", 0))
            if 0 < epoch < max_epoch:
                stale.append(e)
                continue
            max_epoch = max(max_epoch, epoch)
            valid.append(e)
        if stale:
            with self._lock:
                fresh = [e for e in stale
                         if e["version"] not in self._stale_seen]
                self._stale_seen.update(e["version"] for e in fresh)
            if fresh:
                telemetry.count("fleet/stale_publishes_rejected",
                                len(fresh))
                Log.warning(
                    "fleet: rejected %d stale-epoch publish(es): %s",
                    len(fresh),
                    ", ".join("v%d@e%d" % (e["version"],
                                           int(e.get("lease_epoch", 0)))
                              for e in fresh))
        return valid, max_version, max_epoch, stale

    def latest_publish(self) -> Optional[Dict[str, Any]]:
        """Newest valid (non-stale-epoch) publish event whose artifact
        exists on disk, or None. Re-reads the log, so a replica polling
        this sees other processes' publishes."""
        valid, max_version, _max_epoch, _stale = self._scan_publishes()
        if not valid:
            return None
        latest = valid[-1]
        if not os.path.exists(self.artifact_path(latest["version"])):
            return None
        with self._lock:
            if max_version > self._last_version:
                self._last_version = max_version
        return latest

    def latest_valid_publish(self, min_version: int = 0
                             ) -> Optional[Tuple[Dict[str, Any], str]]:
        """Newest publish (newer than ``min_version``) whose artifact
        verifies against the event's sha256/length — walking back past
        corrupt or missing artifacts to the previous good publish, each
        counted once per version under ``fleet/corrupt_artifacts``.
        Returns (event, model_str) or None."""
        valid, _maxv, _maxe, _stale = self._scan_publishes()
        for e in reversed(valid):
            version = int(e["version"])
            if version <= int(min_version):
                break
            try:
                return e, self.load_publish(e)
            except (CorruptArtifactError, OSError) as exc:
                with self._lock:
                    seen = version in self._corrupt_seen
                    self._corrupt_seen.add(version)
                if not seen:
                    telemetry.count("fleet/corrupt_artifacts")
                    Log.warning("fleet: skipping publish v%d (%s: %s); "
                                "falling back to previous good publish",
                                version, type(exc).__name__, exc)
        return None

    def artifact_path(self, version: int) -> str:
        return os.path.join(self._models_dir, _ARTIFACT_FMT % int(version))

    def _read_artifact(self, version: int) -> bytes:
        act = chaos.hit("store/artifact_read")
        with open(self.artifact_path(version), "rb") as f:
            data = f.read()
        if act is not None and act[0] == "torn":
            data = data[:int(len(data) * float(act[1]))]
        return data

    def load_model(self, version: int) -> str:
        """The whole-model string published under ``version`` — raw read,
        no checksum (prefer :meth:`load_publish`)."""
        return self._read_artifact(version).decode("utf-8")

    def load_publish(self, event: Dict[str, Any]) -> str:
        """Read the artifact behind one publish event, verifying the
        event's sha256 + byte length when present. Raises
        :class:`CorruptArtifactError` on mismatch."""
        data = self._read_artifact(int(event["version"]))
        _verify_artifact(event, data)
        return data.decode("utf-8")

    def publishes(self) -> List[Dict[str, Any]]:
        """Valid (non-stale-epoch) publish events oldest-first."""
        valid, _maxv, _maxe, _stale = self._scan_publishes()
        return valid

    # ---------------------------------------------------------------- orphans
    def _reap_orphans(self, max_version: int, grace_s: float) -> None:
        """Delete artifact files no publish event references (a publisher
        died between the artifact ``os.replace`` and its event append)
        plus stray ``*.tmp.*`` files — both only when older than
        ``grace_s``, so opening a store never races a live publish."""
        now = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        reaped = 0
        try:
            names = os.listdir(self._models_dir)
        except OSError:
            return
        for name in names:
            path = os.path.join(self._models_dir, name)
            orphan = False
            if ".tmp." in name:
                orphan = True
            elif name.startswith("v") and name.endswith(".txt"):
                try:
                    orphan = int(name[1:-4]) > max_version
                except ValueError:
                    continue
            if not orphan:
                continue
            try:
                if now - os.path.getmtime(path) < grace_s:
                    continue
                os.unlink(path)
                reaped += 1
            except OSError:
                continue
        if reaped:
            self._orphans_reaped = reaped
            telemetry.count("fleet/orphan_artifacts_reaped", reaped)
            Log.info("fleet: reaped %d orphan artifact file(s) in %s",
                     reaped, self._models_dir)

    # ------------------------------------------------------------- compaction
    def compact(self, *, watermark: int, wins: int, keep_rows: int,
                keep_artifacts: int = 0,
                snapshot_rows: int = 0) -> Dict[str, Any]:
        """Snapshot trainer state and truncate the replayed prefix.

        Writes one ``compact`` record carrying the gate snapshot
        (``watermark``/``wins`` — standing in for every dropped gate
        event), the global row offset of the first retained ingest
        (``row_base``), and the version/epoch floors for dropped
        publishes; then atomically rewrites ``events.jsonl`` as
        [compact record] + retained publishes + retained ingests.

        Retention keeps every ingest chunk with rows above ``watermark``
        (still-unconsumed training traffic) plus the maximal contiguous
        suffix of earlier chunks totalling ≤ ``keep_rows`` rows — because
        the shadow window drops oldest-first chunk-wise, replaying any
        suffix that covers its final content reproduces it bit-for-bit
        (pinned in tests/test_failover.py, including a compaction landing
        mid-shadow-window). Pass the shadow window's capacity as
        ``keep_rows``.

        ``keep_artifacts`` > 0 additionally retains only that many newest
        VALID publish events (stale-epoch zombie publishes never fill the
        retention window — they are dropped and their artifacts deleted;
        the compact record's version/epoch floors stand in for them) and
        deletes the unretained artifact files; 0 keeps all publishes.

        ``snapshot_rows`` > 0 turns on **snapshot bootstrap** mode: the
        retained ingest chunks (the retention rule above, with the keep
        floor raised to ``max(keep_rows, snapshot_rows)``) are written
        to ONE versioned snapshot artifact under ``snapshots/`` instead
        of back into the log, and the compact record carries the
        snapshot's id + sha256 + byte length. A cold standby then
        bootstraps from snapshot + log tail — one sequential blob read
        (or one HTTP GET) instead of replaying per-chunk JSONL — and a
        later compaction splices the previous snapshot's chunks back
        into its retention scan, so nothing covered by the shadow window
        is ever silently dropped across snapshot generations. Replay of
        snapshot + tail is bit-identical to full-log replay (pinned in
        tests/test_control.py, including a mid-shadow-window cut).

        Returns a summary dict. The whole snapshot→rewrite section holds
        the cross-process events writer mutex: a standby trainer's
        ingest append from another process blocks until the ``os.replace``
        lands instead of dying with the old inode (in-process appends are
        additionally serialized by the store lock)."""
        self._assert_writable()
        with self._lock, self._writer_mutex():
            events = list(self.events())
            row_base = 0
            last_version = 0
            lease_epoch = 0
            snap_floor = 0
            ingests: List[Tuple[int, int, Dict[str, Any]]] = []
            # (event, is_stale) — staleness mirrors _scan_publishes:
            # a non-zero epoch below the running max (which includes
            # prior compact records' floors) is a zombie's write
            publishes: List[Tuple[Dict[str, Any], bool]] = []
            seen = None
            for e in events:
                kind = e.get("kind")
                if kind == "compact":
                    snap = e.get("snapshot")
                    if isinstance(snap, dict):
                        snap_floor = max(snap_floor,
                                         int(snap.get("id", 0)))
                        # splice the previous snapshot's chunks back in
                        # as virtual ingest events at their original
                        # offsets: this compaction's retention (and its
                        # own snapshot, if any) sees one uniform
                        # contiguous chunk list
                        for lo, hi, ev in self.snapshot_chunks(e):
                            ingests.append((lo, hi, ev))
                    base = int(e.get("row_base", 0))
                    seen = base if seen is None else seen
                    row_base = base
                    last_version = max(last_version,
                                       int(e.get("last_version", 0)))
                    lease_epoch = max(lease_epoch,
                                      int(e.get("lease_epoch", 0)))
                elif kind == "ingest":
                    lo = row_base if seen is None else seen
                    seen = lo + int(e.get("n", 0))
                    ingests.append((lo, seen, e))
                elif kind == "publish":
                    v = e.get("version")
                    is_stale = False
                    if isinstance(v, int):
                        last_version = max(last_version, v)
                        epoch = int(e.get("lease_epoch", 0))
                        is_stale = 0 < epoch < lease_epoch
                        lease_epoch = max(lease_epoch, epoch)
                    publishes.append((e, is_stale))
            total_rows = ingests[-1][1] if ingests else row_base
            # the earliest row any replay could still reconstruct before
            # this compaction (spliced snapshot chunks included) — the
            # baseline dropped_rows is measured against
            old_floor = ingests[0][0] if ingests else row_base
            # retained = mandatory unconsumed suffix + shadow-cover
            # suffix; snapshot mode raises the keep floor so the
            # snapshot warms at least snapshot_rows of recent traffic
            eff_keep = int(keep_rows)
            if int(snapshot_rows) > 0:
                eff_keep = max(eff_keep, int(snapshot_rows))
            keep_from = len(ingests)
            acc = 0
            for i in range(len(ingests) - 1, -1, -1):
                lo, hi, e = ingests[i]
                n = int(e.get("n", 0))
                if hi > int(watermark) or acc + n <= eff_keep:
                    acc += n
                    keep_from = i
                else:
                    break
            kept_ingests = ingests[keep_from:]
            new_row_base = kept_ingests[0][0] if kept_ingests else total_rows
            kept_publishes = [e for e, _ in publishes]
            dropped_artifacts = 0
            if int(keep_artifacts) > 0:
                valid_pubs = [e for e, is_stale in publishes
                              if not is_stale]
                kept_publishes = valid_pubs[-int(keep_artifacts):]
                kept_versions = {int(e["version"]) for e in kept_publishes
                                 if isinstance(e.get("version"), int)}
                for e, _ in publishes:
                    v = e.get("version")
                    if isinstance(v, int) and v not in kept_versions:
                        try:
                            os.unlink(self.artifact_path(v))
                            dropped_artifacts += 1
                        except OSError:
                            pass
            snap_section = None
            if int(snapshot_rows) > 0 and kept_ingests:
                snap_section = self._write_snapshot(
                    snap_floor + 1, new_row_base, int(total_rows),
                    kept_ingests)
            record = self._stamp("compact", {
                "watermark": int(watermark), "wins": int(wins),
                # with a snapshot the log itself keeps NO ingest lines:
                # its row offsets resume at total_rows and the snapshot
                # section carries the preserved [row_base, top_row) span
                "row_base": int(total_rows) if snap_section is not None
                else int(new_row_base),
                "last_version": int(last_version),
                "lease_epoch": int(lease_epoch),
                # clamped: spliced snapshot chunks are not log lines, so
                # they can outnumber the events they were folded from
                "dropped_events": max(0, len(events) - len(kept_ingests)
                                      - len(kept_publishes)),
                "dropped_rows": int(new_row_base - old_floor)})
            if snap_section is not None:
                record["snapshot"] = snap_section
            lines = [record] + kept_publishes
            if snap_section is None:
                lines += [e for _, _, e in kept_ingests]
            tmp = self._events_path + ".tmp.%d" % os.getpid()
            data = "".join(json.dumps(entry, sort_keys=True) + "\n"
                           for entry in lines).encode("utf-8")
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                view = memoryview(data)
                done = 0
                while done < len(view):
                    done += os.write(fd, view[done:])
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, self._events_path)
            self._compactions += 1
            self._last_compact_ts = record["ts"]
            if last_version > self._last_version:
                self._last_version = last_version
        telemetry.count("fleet/compactions")
        telemetry.count("fleet/compacted_events",
                        max(0, int(record["dropped_events"])))
        telemetry.count("fleet/compacted_rows",
                        max(0, int(record["dropped_rows"])))
        telemetry.gauge("fleet/events_log_bytes", self.log_bytes())
        telemetry.gauge("fleet/last_compaction_ts", record["ts"])
        Log.info("fleet: compacted %s: dropped %d event(s) / %d row(s) "
                 "/ %d artifact(s), kept %d ingest + %d publish",
                 self._model_id, record["dropped_events"],
                 record["dropped_rows"], dropped_artifacts,
                 len(kept_ingests), len(kept_publishes))
        return {"dropped_events": record["dropped_events"],
                "dropped_rows": record["dropped_rows"],
                "dropped_artifacts": dropped_artifacts,
                "row_base": int(new_row_base),
                "snapshot": snap_section,
                "log_bytes": self.log_bytes()}

    # -------------------------------------------------------------- snapshots
    def snapshot_path(self, sid: int) -> str:
        return os.path.join(self._snapshots_dir, _SNAPSHOT_FMT % int(sid))

    def _scan_snapshot_ids(self) -> List[int]:
        try:
            names = os.listdir(self._snapshots_dir)
        except OSError:
            return []
        ids = []
        for name in names:
            if name.startswith("s") and name.endswith(".json"):
                try:
                    ids.append(int(name[1:-5]))
                except ValueError:
                    continue
        return sorted(ids)

    def _write_snapshot(self, sid_min: int, row_base: int, top_row: int,
                        kept_ingests: List[Tuple[int, int, Dict[str, Any]]]
                        ) -> Dict[str, Any]:
        """Write the retained ingest chunks to one versioned snapshot
        blob (``snapshots/s%06d.json``, tmp + fsync + ``os.replace``) and
        return the ``snapshot`` section for the compact record. The
        chunks carry their original ingest events verbatim plus their
        global row offsets, so replaying snapshot + tail is bit-identical
        to replaying the uncompacted log. Ids are monotonic across
        generations (never below ``sid_min``, the prior snapshot's id +
        1, even if its file was already pruned); older snapshot files are
        pruned after the replace — the log's compact record is the only
        pointer, and it always points at the newest."""
        os.makedirs(self._snapshots_dir, exist_ok=True)
        existing = self._scan_snapshot_ids()
        sid = max(int(sid_min), (existing[-1] + 1) if existing else 1)
        doc = {"v": STORE_VERSION, "kind": "snapshot", "id": sid,
               "model_id": self._model_id,
               "row_base": int(row_base), "top_row": int(top_row),
               "chunks": [{"lo": int(lo), "event": e}
                          for lo, _hi, e in kept_ingests]}
        data = json.dumps(doc, sort_keys=True).encode("utf-8")
        path = self.snapshot_path(sid)
        tmp = path + ".tmp.%d" % os.getpid()
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            view = memoryview(data)
            done = 0
            while done < len(view):
                done += os.write(fd, view[done:])
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        for old in existing:
            if old < sid:
                try:
                    os.unlink(self.snapshot_path(old))
                except OSError:
                    pass
        rows = sum(int(e.get("n", 0)) for _lo, _hi, e in kept_ingests)
        telemetry.count("fleet/snapshots_written")
        telemetry.gauge("fleet/snapshot_bytes", len(data))
        Log.info("fleet: wrote snapshot s%06d for %s: %d row(s) in "
                 "[%d, %d), %d bytes", sid, self._model_id, rows,
                 row_base, top_row, len(data))
        return {"id": sid, "sha256": hashlib.sha256(data).hexdigest(),
                "bytes": len(data), "rows": rows,
                "row_base": int(row_base), "top_row": int(top_row)}

    def snapshot_bytes(self, sid: int) -> bytes:
        """Raw snapshot blob (chaos ``store/artifact_read`` torn actions
        apply, mirroring model-artifact reads)."""
        act = chaos.hit("store/artifact_read")
        with open(self.snapshot_path(sid), "rb") as f:
            data = f.read()
        if act is not None and act[0] == "torn":
            data = data[:int(len(data) * float(act[1]))]
        return data

    def load_snapshot(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Read + verify the snapshot behind one compact record's
        ``snapshot`` section. Raises :class:`CorruptArtifactError` on
        sha256/length mismatch, ``OSError`` when the file is gone."""
        snap = record.get("snapshot") or {}
        data = self.snapshot_bytes(int(snap.get("id", 0)))
        _verify_snapshot(record, data)
        return json.loads(data.decode("utf-8"))

    def snapshot_chunks(self, record: Dict[str, Any]
                        ) -> List[Tuple[int, int, Dict[str, Any]]]:
        """The ingest chunks preserved by ``record``'s snapshot, as
        ``(lo, hi, event)`` at their original global row offsets — what
        replay and the next compaction splice back in place of the log
        lines the snapshot replaced. Degrades to ``[]`` (with a warning)
        when the snapshot is missing or corrupt: because the compact
        record's ``row_base`` already equals the snapshot's ``top_row``,
        later offsets stay consistent — the failure costs buffered rows,
        never misaligns the log."""
        snap = record.get("snapshot")
        if not isinstance(snap, dict):
            return []
        try:
            doc = self.load_snapshot(record)
        except (OSError, ValueError, CorruptArtifactError) as exc:
            telemetry.count("fleet/snapshot_load_failures")
            Log.warning("fleet: snapshot s%06d unreadable (%s); replay "
                        "continues degraded without its %s buffered "
                        "row(s)", int(snap.get("id", 0)), exc,
                        snap.get("rows", "?"))
            return []
        out: List[Tuple[int, int, Dict[str, Any]]] = []
        for c in doc.get("chunks", []):
            ev = c.get("event") or {}
            lo = int(c.get("lo", 0))
            out.append((lo, lo + int(ev.get("n", 0)), ev))
        return out

    # ------------------------------------------------------------- heartbeats
    def record_heartbeat(self, doc: Dict[str, Any]) -> bool:
        """Persist one node heartbeat, latest-wins.

        Heartbeats are observability, not replicated state: each node
        owns ONE small sidecar file under ``heartbeats/`` that is
        atomically replaced on every beat, so N nodes occupy O(N) bytes
        no matter how long they run — heartbeats never touch
        ``events.jsonl`` (replay and compaction stay bit-identical) and
        read-only replica opens may record them (the ``read_only``
        contract protects the event log and artifacts, not sidecar
        observability). Returns False when ``doc`` carries no usable
        ``node`` id."""
        node = str(doc.get("node") or "").strip()
        if not node:
            return False
        entry = self._stamp("heartbeat", dict(doc))
        entry["node"] = node
        fname = re.sub(r"[^A-Za-z0-9_.-]", "_", node)[:80] + ".json"
        os.makedirs(self._heartbeats_dir, exist_ok=True)
        path = os.path.join(self._heartbeats_dir, fname)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            f.write(json.dumps(entry, sort_keys=True))
        os.replace(tmp, path)
        telemetry.count("fleet/heartbeats_recorded")
        return True

    def heartbeats(self, max_age_s: Optional[float] = None
                   ) -> List[Dict[str, Any]]:
        """Latest heartbeat per node (sorted by node id), skipping
        torn/corrupt files; ``max_age_s`` filters out beats from nodes
        that stopped reporting that long ago."""
        try:
            names = sorted(os.listdir(self._heartbeats_dir))
        except OSError:
            return []
        now = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        out: List[Dict[str, Any]] = []
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self._heartbeats_dir, name)) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(doc, dict) or not doc.get("node"):
                continue
            if (max_age_s is not None
                    and now - float(doc.get("ts", 0.0)) > max_age_s):
                continue
            out.append(doc)
        out.sort(key=lambda d: str(d.get("node")))
        return out

    # ------------------------------------------------------------------ state
    def state(self) -> Dict[str, Any]:
        """JSON-serializable store summary (surfaced on /healthz)."""
        with self._lock:
            return {
                "root": self._root,
                "model_id": self._model_id,
                "read_only": self._read_only,
                "last_published_version": self._last_version,
                "publishes_this_process": self._publishes,
                "ingest_rows_persisted": self._ingest_rows,
                "lease": self.lease_state(),
                "events_log_bytes": self.log_bytes(),
                "compactions": self._compactions,
                "last_compaction_ts": self._last_compact_ts,
                "orphan_artifacts_reaped": self._orphans_reaped,
                "heartbeat_nodes": len(self.heartbeats()),
            }
