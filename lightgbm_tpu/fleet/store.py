"""Durable fleet state: one directory per served model.

    <root>/<model_id>/events.jsonl      append-only event log
    <root>/<model_id>/models/v%06d.txt  immutable whole-model artifacts

The event log rides the PR-10 ledger substrate
(:func:`~lightgbm_tpu.obs_ledger.append_jsonl` /
:func:`~lightgbm_tpu.obs_ledger.read_jsonl`): every append is ONE write
call of one JSON line, so concurrent writers (HTTP ingest handlers, the
trainer worker) interleave whole lines and a SIGKILL mid-append leaves at
most one partial line, skipped on read. Three event kinds:

- ``ingest``: one labeled traffic chunk (rows + labels). Replayed on
  boot so a restarted server resumes its shadow window and training
  buffer instead of cold-starting.
- ``gate``: one promotion-gate cycle (result, consecutive-win count for
  promotion hysteresis, the consumed-row watermark separating
  already-trained traffic from still-buffered traffic).
- ``publish``: a whole model became servable under a monotonically
  increasing **version token**. The artifact is written to a temp file
  and ``os.replace``d into place BEFORE the event lands, so a replica
  that sees the event always reads a complete model — whole historical
  models only, never a torn artifact.

Rollbacks are publishes too (``event="rollback"``): replicas converge by
always applying the newest version token, so a rollback distributes
exactly like a promotion.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ..obs import telemetry
from ..obs_ledger import append_jsonl, read_jsonl
from ..utils.log import LightGBMError

#: schema version stamped on every event; readers skip newer majors
STORE_VERSION = 1

#: publish-event reasons (reporting only — replicas apply them all)
PUBLISH_EVENTS = ("boot", "promotion", "rollback")

_ARTIFACT_FMT = "v%06d.txt"


class FleetStore:
    """Durable event log + model-artifact directory for one served model.

    Thread-safe: appends arrive from HTTP handler threads (ingest) and
    the trainer worker (gate/publish); reads come from replica-watcher
    threads and boot-time replay. The in-memory counters exist only for
    cheap ``state()`` snapshots — the file is the source of truth.
    """

    def __init__(self, root: str, model_id: str = "default") -> None:
        model_id = str(model_id)
        if not model_id or "/" in model_id or model_id.startswith("."):
            raise LightGBMError("fleet model_id must be a plain name, "
                                "got %r" % model_id)
        self._root = os.path.abspath(root)
        self._model_id = model_id
        self._dir = os.path.join(self._root, model_id)
        self._events_path = os.path.join(self._dir, "events.jsonl")
        self._models_dir = os.path.join(self._dir, "models")
        os.makedirs(self._models_dir, exist_ok=True)
        # guards version allocation and the state counters; file appends
        # are one-write atomic on their own but publish must allocate the
        # next version token and write the artifact before its event
        self._lock = threading.Lock()
        latest = self._scan_latest_publish()
        self._last_version = latest["version"] if latest else 0
        self._ingest_rows = 0
        self._publishes = 0

    # ---------------------------------------------------------------- identity
    @property
    def root(self) -> str:
        return self._root

    @property
    def model_id(self) -> str:
        return self._model_id

    @property
    def events_path(self) -> str:
        return self._events_path

    # ----------------------------------------------------------------- append
    def _stamp(self, kind: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        entry = {"v": STORE_VERSION, "kind": kind,
                 "ts": time.time()}  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        entry.update(payload)
        return entry

    def append_ingest(self, X, y) -> None:
        """Persist one labeled traffic chunk (one JSONL line). Called on
        the ingest path BEFORE the in-memory buffer push, so a crash
        after the append replays the chunk instead of losing it."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        y = np.asarray(y, np.float64).ravel()
        append_jsonl(self._events_path, self._stamp("ingest", {
            "n": int(len(y)), "rows": X.tolist(), "labels": y.tolist()}))
        with self._lock:
            self._ingest_rows += int(len(y))
        telemetry.count("fleet/ingest_rows_persisted", int(len(y)))

    def append_gate(self, result: str, wins: int, consumed_rows: int,
                    losses: Optional[Dict[str, float]] = None) -> None:
        """Persist one promotion-gate cycle: its verdict, the
        consecutive-win counter (promotion-hysteresis state a restarted
        trainer must resume), and the consumed-row watermark (rows
        ingested before it are already trained — replay keeps them out
        of the training buffer but in the shadow window)."""
        append_jsonl(self._events_path, self._stamp("gate", {
            "result": str(result), "wins": int(wins),
            "consumed_rows": int(consumed_rows),
            "losses": losses}))

    # ---------------------------------------------------------------- publish
    def publish(self, model_str: str, event: str = "promotion",
                meta: Optional[Dict[str, Any]] = None) -> int:
        """Publish one whole model under the next version token.

        The artifact is written to a temp path and ``os.replace``d (atomic
        on POSIX) before the publish event is appended — a watcher that
        sees the event can always read the complete artifact. Returns the
        allocated version token."""
        if event not in PUBLISH_EVENTS:
            raise LightGBMError("publish event must be one of %s, got %r"
                                % ("|".join(PUBLISH_EVENTS), event))
        with self._lock:
            version = self._last_version + 1
            name = _ARTIFACT_FMT % version
            final = os.path.join(self._models_dir, name)
            tmp = final + ".tmp.%d" % os.getpid()
            view = memoryview(model_str.encode("utf-8"))
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                done = 0
                while done < len(view):
                    done += os.write(fd, view[done:])
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, final)
            append_jsonl(self._events_path, self._stamp("publish", {
                "version": version, "artifact": name, "event": event,
                "meta": dict(meta) if meta else None}))
            self._last_version = version
            self._publishes += 1
        telemetry.count("fleet/publishes")
        telemetry.gauge("fleet/published_version", version)
        return version

    # ------------------------------------------------------------------ read
    def events(self, kind: Optional[str] = None) -> Iterator[Dict[str, Any]]:
        """Events oldest-first (corrupt/partial lines skipped)."""
        for e in read_jsonl(self._events_path, max_version=STORE_VERSION):
            if kind is None or e.get("kind") == kind:
                yield e

    def _scan_latest_publish(self) -> Optional[Dict[str, Any]]:
        latest: Optional[Dict[str, Any]] = None
        for e in self.events("publish"):
            v = e.get("version")
            if isinstance(v, int) and (latest is None
                                       or v > latest["version"]):
                latest = e
        return latest

    def latest_publish(self) -> Optional[Dict[str, Any]]:
        """Newest publish event whose artifact exists on disk, or None.
        Re-reads the log, so a replica polling this sees other
        processes' publishes."""
        latest = self._scan_latest_publish()
        if latest is None:
            return None
        if not os.path.exists(self.artifact_path(latest["version"])):
            return None
        with self._lock:
            if latest["version"] > self._last_version:
                self._last_version = latest["version"]
        return latest

    def artifact_path(self, version: int) -> str:
        return os.path.join(self._models_dir, _ARTIFACT_FMT % int(version))

    def load_model(self, version: int) -> str:
        """The whole-model string published under ``version``."""
        with open(self.artifact_path(version), "r", encoding="utf-8") as f:
            return f.read()

    def publishes(self) -> List[Dict[str, Any]]:
        """All publish events oldest-first."""
        return list(self.events("publish"))

    # ------------------------------------------------------------------ state
    def state(self) -> Dict[str, Any]:
        """JSON-serializable store summary (surfaced on /healthz)."""
        with self._lock:
            return {
                "root": self._root,
                "model_id": self._model_id,
                "last_published_version": self._last_version,
                "publishes_this_process": self._publishes,
                "ingest_rows_persisted": self._ingest_rows,
            }
