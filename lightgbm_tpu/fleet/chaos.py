"""Deterministic fault injection for the fleet layer.

The durability claims in this package (torn appends are skipped on
replay, a zombie trainer's publishes are fenced, a replica survives
dropped connections and torn artifact reads) are only claims until a
test can *make* those faults happen on demand. This module is the
switchboard: production code calls :func:`hit` at named failure points,
and a test installs a :class:`FaultPlan` — an explicit, seeded,
per-point FIFO of actions — so every fault fires at a deterministic
call count, never off a wall-clock race.

Failure points (the strings passed to :func:`hit`):

- ``store/append``        before an event-log line is written
- ``store/publish``       after artifact replace, before the event lands
- ``store/artifact_read`` before a model artifact is read back
- ``store/lease``         before a lease record is replaced
- ``transport/request``   client side, before an HTTP request is issued
- ``transport/serve``     server side, before a /fleet response is sent

Actions are tuples: ``("raise", exc)`` raises inside :func:`hit`;
``("sleep", seconds)`` stalls inside :func:`hit` (slow store / slow
response); ``("torn", fraction)`` is RETURNED to the caller, which is
responsible for truncating its write/read/response body to that
fraction — tearing is inherently caller-specific. Two fleet-control
kinds ride the same queues: ``("partition", n)`` makes the point fail
``n`` CONSECUTIVE times (it raises and re-queues itself at the front
with ``n-1``, so one action simulates an endpoint dark for a whole
window of requests, not one random drop); ``("reorder",)`` is returned
to the caller like torn — the append path parks the entry it was about
to write (:meth:`FaultPlan.park`) and lands it right AFTER its
successor (:meth:`FaultPlan.take_parked`), the delayed-write-past-its-
successor race a replicated log must tolerate. With no plan installed
``hit`` is one global load and a None check, so the hooks cost nothing
in production.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..obs import telemetry

#: every failure point production code calls hit() with, for validation
FAILURE_POINTS = (
    "store/append",
    "store/publish",
    "store/artifact_read",
    "store/lease",
    "transport/request",
    "transport/serve",
)


class InjectedFault(Exception):
    """Default exception for ("raise", ...) actions — distinguishable
    from real faults in test assertions and log lines."""


Action = Tuple[Any, ...]


class FaultPlan:
    """A per-point FIFO of fault actions, consumed by :func:`hit`.

    Build one explicitly (``FaultPlan({"store/append": [("torn", 0.5)]})``)
    when a test needs one exact fault at one exact call, or with
    :meth:`seeded` when a scenario wants *many* faults whose mix is
    reproducible from a single integer. Consumption is thread-safe; the
    schedule itself is fixed at construction so two runs with the same
    plan inject identically regardless of thread timing per point.
    """

    def __init__(self, actions: Optional[Dict[str, Sequence[Action]]] = None
                 ) -> None:
        self._lock = threading.Lock()
        self._queues: Dict[str, List[Action]] = {}
        self._injected: Dict[str, int] = {}
        self._parked: Dict[str, List[Any]] = {}
        for point, acts in (actions or {}).items():
            self.add(point, *acts)

    #: seeded() default mix — frozen so pre-existing seeds keep their
    #: byte-identical schedules; scenarios opt into the control-plane
    #: kinds with kinds=KINDS_ALL
    KINDS_DEFAULT = ("raise", "torn", "sleep")
    KINDS_ALL = ("raise", "torn", "sleep", "partition", "reorder")

    @classmethod
    def seeded(cls, seed: int, counts: Dict[str, int], *,
               sleep_s: float = 0.05,
               kinds: Sequence[str] = KINDS_DEFAULT) -> "FaultPlan":
        """A plan with ``counts[point]`` faults per point, the action mix
        drawn deterministically from ``random.Random(seed)``. Same seed +
        counts → byte-identical schedule, independent of wall clock.
        ``kinds`` selects the mix (uniform over the tuple): the default
        keeps the original raise/torn/sleep stream so existing seeds
        reproduce; :data:`KINDS_ALL` adds partition/reorder for the
        write-surface drills."""
        rng = random.Random(int(seed))
        plan = cls()
        kinds = tuple(kinds)
        legacy = kinds == cls.KINDS_DEFAULT
        for point in sorted(counts):
            for _ in range(int(counts[point])):
                roll = rng.random()
                if legacy:
                    # the frozen original thresholds + draw order: same
                    # seed → the exact schedule every pre-existing
                    # chaos scenario was tuned against
                    kind = ("raise" if roll < 0.4
                            else "torn" if roll < 0.7 else "sleep")
                else:
                    kind = kinds[min(int(roll * len(kinds)),
                                     len(kinds) - 1)]
                if kind == "raise":
                    act: Action = ("raise",
                                   InjectedFault("chaos@%s" % point))
                elif kind == "torn":
                    act = ("torn", 0.1 + 0.8 * rng.random())
                elif kind == "partition":
                    act = ("partition", 1 + int(rng.random() * 3))
                elif kind == "reorder":
                    act = ("reorder",)
                else:
                    act = ("sleep", sleep_s * rng.random())
                plan.add(point, act)
        return plan

    def add(self, point: str, *actions: Action) -> "FaultPlan":
        if point not in FAILURE_POINTS:
            raise ValueError("unknown chaos point %r (known: %s)"
                             % (point, ", ".join(FAILURE_POINTS)))
        with self._lock:
            self._queues.setdefault(point, []).extend(actions)
        return self

    def push_front(self, point: str, *actions: Action) -> "FaultPlan":
        """Queue ``actions`` ahead of everything pending at ``point`` —
        how a ("partition", n) action re-queues its remaining n-1
        failures so they hit the very next requests."""
        if point not in FAILURE_POINTS:
            raise ValueError("unknown chaos point %r (known: %s)"
                             % (point, ", ".join(FAILURE_POINTS)))
        with self._lock:
            self._queues.setdefault(point, [])[:0] = list(actions)
        return self

    def next_action(self, point: str) -> Optional[Action]:
        with self._lock:
            queue = self._queues.get(point)
            if not queue:
                return None
            self._injected[point] = self._injected.get(point, 0) + 1
            return queue.pop(0)

    def park(self, point: str, obj: Any) -> None:
        """Reorder support: hold ``obj`` (an event the caller was about
        to write) until the next write at ``point`` lands, then the
        caller drains it via :meth:`take_parked` — the parked entry hits
        the log AFTER its successor."""
        with self._lock:
            self._parked.setdefault(point, []).append(obj)

    def take_parked(self, point: str) -> List[Any]:
        with self._lock:
            return self._parked.pop(point, [])

    def pending(self) -> Dict[str, int]:
        with self._lock:
            return {p: len(q) for p, q in self._queues.items() if q}

    def injected(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._injected)


#: the installed plan; None (the fast path) outside chaos tests
_active: Optional[FaultPlan] = None  # graftlint: disable=module-mutable-state -- test-only injection switchboard, installed/uninstalled under _active_lock
_active_lock = threading.Lock()  # graftlint: disable=module-mutable-state -- guards _active install/uninstall


def install(plan: FaultPlan) -> None:
    global _active
    with _active_lock:
        _active = plan


def uninstall() -> None:
    global _active
    with _active_lock:
        _active = None


class inject:
    """``with chaos.inject(plan): ...`` — install for the block, always
    uninstall after, so a failing test can't leak faults into the next."""

    def __init__(self, plan: FaultPlan) -> None:
        self._plan = plan

    def __enter__(self) -> FaultPlan:
        install(self._plan)
        return self._plan

    def __exit__(self, *exc) -> None:
        uninstall()


def active() -> Optional[FaultPlan]:
    return _active


def hit(point: str) -> Optional[Action]:
    """Consume one fault at ``point`` if a plan is installed.

    Raises for ("raise", exc) and ("partition", n) actions (a partition
    additionally re-queues itself at the front with n-1, so the point
    stays dark for n consecutive calls), stalls for ("sleep", s)
    actions, and returns ("torn", fraction) / ("reorder",) for the
    caller to apply. Returns None (and does nothing) when no plan is
    installed or the point's queue is empty."""
    plan = _active
    if plan is None:
        return None
    act = plan.next_action(point)
    if act is None:
        return None
    telemetry.count("chaos/injected/" + point)
    kind = act[0]
    if kind == "raise":
        exc = act[1]
        if isinstance(exc, BaseException):
            raise exc
        raise exc("chaos@%s" % point)
    if kind == "partition":
        remaining = int(act[1])
        if remaining > 1:
            plan.push_front(point, ("partition", remaining - 1))
        raise InjectedFault("partition@%s (%d request(s) left dark)"
                            % (point, remaining))
    if kind == "sleep":
        time.sleep(float(act[1]))
        return None
    return act
