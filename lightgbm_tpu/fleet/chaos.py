"""Deterministic fault injection for the fleet layer.

The durability claims in this package (torn appends are skipped on
replay, a zombie trainer's publishes are fenced, a replica survives
dropped connections and torn artifact reads) are only claims until a
test can *make* those faults happen on demand. This module is the
switchboard: production code calls :func:`hit` at named failure points,
and a test installs a :class:`FaultPlan` — an explicit, seeded,
per-point FIFO of actions — so every fault fires at a deterministic
call count, never off a wall-clock race.

Failure points (the strings passed to :func:`hit`):

- ``store/append``        before an event-log line is written
- ``store/publish``       after artifact replace, before the event lands
- ``store/artifact_read`` before a model artifact is read back
- ``store/lease``         before a lease record is replaced
- ``transport/request``   client side, before an HTTP request is issued
- ``transport/serve``     server side, before a /fleet response is sent

Actions are tuples: ``("raise", exc)`` raises inside :func:`hit`;
``("sleep", seconds)`` stalls inside :func:`hit` (slow store / slow
response); ``("torn", fraction)`` is RETURNED to the caller, which is
responsible for truncating its write/read/response body to that
fraction — tearing is inherently caller-specific. With no plan
installed ``hit`` is one global load and a None check, so the hooks
cost nothing in production.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..obs import telemetry

#: every failure point production code calls hit() with, for validation
FAILURE_POINTS = (
    "store/append",
    "store/publish",
    "store/artifact_read",
    "store/lease",
    "transport/request",
    "transport/serve",
)


class InjectedFault(Exception):
    """Default exception for ("raise", ...) actions — distinguishable
    from real faults in test assertions and log lines."""


Action = Tuple[Any, ...]


class FaultPlan:
    """A per-point FIFO of fault actions, consumed by :func:`hit`.

    Build one explicitly (``FaultPlan({"store/append": [("torn", 0.5)]})``)
    when a test needs one exact fault at one exact call, or with
    :meth:`seeded` when a scenario wants *many* faults whose mix is
    reproducible from a single integer. Consumption is thread-safe; the
    schedule itself is fixed at construction so two runs with the same
    plan inject identically regardless of thread timing per point.
    """

    def __init__(self, actions: Optional[Dict[str, Sequence[Action]]] = None
                 ) -> None:
        self._lock = threading.Lock()
        self._queues: Dict[str, List[Action]] = {}
        self._injected: Dict[str, int] = {}
        for point, acts in (actions or {}).items():
            self.add(point, *acts)

    @classmethod
    def seeded(cls, seed: int, counts: Dict[str, int], *,
               sleep_s: float = 0.05) -> "FaultPlan":
        """A plan with ``counts[point]`` faults per point, the action mix
        drawn deterministically from ``random.Random(seed)``. Same seed +
        counts → byte-identical schedule, independent of wall clock."""
        rng = random.Random(int(seed))
        plan = cls()
        for point in sorted(counts):
            for _ in range(int(counts[point])):
                roll = rng.random()
                if roll < 0.4:
                    act: Action = ("raise",
                                   InjectedFault("chaos@%s" % point))
                elif roll < 0.7:
                    act = ("torn", 0.1 + 0.8 * rng.random())
                else:
                    act = ("sleep", sleep_s * rng.random())
                plan.add(point, act)
        return plan

    def add(self, point: str, *actions: Action) -> "FaultPlan":
        if point not in FAILURE_POINTS:
            raise ValueError("unknown chaos point %r (known: %s)"
                             % (point, ", ".join(FAILURE_POINTS)))
        with self._lock:
            self._queues.setdefault(point, []).extend(actions)
        return self

    def next_action(self, point: str) -> Optional[Action]:
        with self._lock:
            queue = self._queues.get(point)
            if not queue:
                return None
            self._injected[point] = self._injected.get(point, 0) + 1
            return queue.pop(0)

    def pending(self) -> Dict[str, int]:
        with self._lock:
            return {p: len(q) for p, q in self._queues.items() if q}

    def injected(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._injected)


#: the installed plan; None (the fast path) outside chaos tests
_active: Optional[FaultPlan] = None  # graftlint: disable=module-mutable-state -- test-only injection switchboard, installed/uninstalled under _active_lock
_active_lock = threading.Lock()  # graftlint: disable=module-mutable-state -- guards _active install/uninstall


def install(plan: FaultPlan) -> None:
    global _active
    with _active_lock:
        _active = plan


def uninstall() -> None:
    global _active
    with _active_lock:
        _active = None


class inject:
    """``with chaos.inject(plan): ...`` — install for the block, always
    uninstall after, so a failing test can't leak faults into the next."""

    def __init__(self, plan: FaultPlan) -> None:
        self._plan = plan

    def __enter__(self) -> FaultPlan:
        install(self._plan)
        return self._plan

    def __exit__(self, *exc) -> None:
        uninstall()


def active() -> Optional[FaultPlan]:
    return _active


def hit(point: str) -> Optional[Action]:
    """Consume one fault at ``point`` if a plan is installed.

    Raises for ("raise", exc) actions, stalls for ("sleep", s) actions,
    and returns ("torn", fraction) for the caller to apply. Returns None
    (and does nothing) when no plan is installed or the point's queue is
    empty."""
    plan = _active
    if plan is None:
        return None
    act = plan.next_action(point)
    if act is None:
        return None
    telemetry.count("chaos/injected/" + point)
    kind = act[0]
    if kind == "raise":
        exc = act[1]
        if isinstance(exc, BaseException):
            raise exc
        raise exc("chaos@%s" % point)
    if kind == "sleep":
        time.sleep(float(act[1]))
        return None
    return act
