"""Serving replicas: watch the fleet store, hot-swap whole models.

One trainer process publishes promoted models as version-tokened
artifacts (:meth:`~lightgbm_tpu.fleet.store.FleetStore.publish`); each
serving replica runs a :class:`ReplicaWatcher` that polls the store and
adopts newer versions through the existing ``Booster.adopt`` path — the
same single-version-bump atomic swap the in-process online trainer uses,
so every concurrent ``PredictSession`` snapshot on the replica sees the
old ensemble or the new one whole. This is the single-trainer /
many-workers decomposition of arXiv:1611.01276 applied to serving:
replicas never train, they only apply whole historical models.

The store is duck-typed: a filesystem
:class:`~lightgbm_tpu.fleet.store.FleetStore` or a
:class:`~lightgbm_tpu.fleet.transport.RemoteStore` polling a trainer's
``/fleet`` endpoints over HTTP — the watcher code is identical. Loads
go through ``latest_valid_publish``, which verifies each artifact
against the sha256 + length in its publish event and walks back to the
previous good publish past corruption; stale-epoch publishes from a
fenced-off zombie trainer are rejected inside the store scan. A failing
store backs the poll off exponentially (capped, reset on first success)
so a dead store is not hammered at ``poll_interval_s``.

Rollbacks distribute the same way: the trainer publishes the restored
model under a NEW version token, and replicas converge by always
applying the newest token (exactly one local version bump per applied
publish — pinned in tests/test_fleet.py).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..obs import telemetry
from ..obs_trace import tracer
from ..utils.log import LightGBMError, Log


def bootstrap_model(store):
    """(booster, version) from the store's newest verified publish, or
    (None, 0) when nothing usable was published yet (the replica then
    needs an ``input_model`` to boot from)."""
    loaded = store.latest_valid_publish(0)
    if loaded is None:
        return None, 0
    event, model_str = loaded
    from ..basic import Booster
    return Booster(model_str=model_str), int(event["version"])


class _ArtifactLoader:
    """Thread-confined model build for one swap: constructed fresh per
    applied publish, so the candidate booster it parses is private to
    that poll (graftlint's thread-reachability stops at a freshly-
    constructed receiver — the online trainer's _CandidateBuilder
    pattern), and the only shared-model call left on the poller thread
    is the lock-guarded ``adopt``."""

    def __init__(self, store) -> None:
        self._store = store

    def fetch(self, min_version: int):
        """(event, candidate booster) for the newest verified publish
        past ``min_version``, or None."""
        loaded = self._store.latest_valid_publish(min_version)
        if loaded is None:
            return None
        event, model_str = loaded
        from ..basic import Booster
        return event, Booster(model_str=model_str)


class ReplicaWatcher:
    """Poll the store for newer published versions and hot-swap them
    into one serving booster.

    ``start=True`` (default) runs a named daemon thread polling every
    ``poll_interval_s``; tests drive :meth:`poll_once` synchronously with
    ``start=False``. Each applied publish is one ``Booster.adopt`` — one
    version bump, whole model, never a partial state. Poll failures back
    off exponentially up to ``backoff_max_s`` (gauge
    ``fleet/poll_backoff_ms``), reset by the next success.
    """

    def __init__(self, booster, store, *,
                 poll_interval_s: float = 0.5,
                 applied_version: int = 0,
                 backoff_max_s: float = 10.0,
                 start: bool = True) -> None:
        if poll_interval_s <= 0:
            raise LightGBMError("fleet poll_interval_s must be > 0, "
                                "got %g" % poll_interval_s)
        if backoff_max_s < poll_interval_s:
            raise LightGBMError("fleet backoff_max_s must be >= "
                                "poll_interval_s, got %g < %g"
                                % (backoff_max_s, poll_interval_s))
        self._booster = booster
        self._store = store
        self._poll = float(poll_interval_s)
        self._backoff_max = float(backoff_max_s)
        # guards the applied-version token, the swap counters and the
        # error-backoff state (the poller thread writes them, /healthz
        # handler threads read), and doubles as the poller's wakeup so
        # close() never waits a full poll interval
        self._lock = threading.Condition()
        self._applied = int(applied_version)
        self._swaps = 0
        self._errors = 0
        self._backoff = 0.0
        self._last_error = ""
        self._last_swap_ts = 0.0
        self._stopped = False
        telemetry.gauge("fleet/applied_version", self._applied)
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._worker, name="lgbtpu-fleet-replica",
                daemon=True)
            self._thread.start()

    # ----------------------------------------------------------------- polling
    def poll_once(self) -> bool:
        """Check the store once; adopt a newer version if one was
        published. Returns True when a swap happened."""
        latest = self._store.latest_publish()
        if latest is None:
            return False
        with self._lock:
            applied = self._applied
        if int(latest["version"]) <= applied:
            return False
        # checksum-verified fetch, falling back past corrupt artifacts;
        # build the private candidate off-lock, then adopt — ONE version
        # bump, whole-model invariant held
        loaded = _ArtifactLoader(self._store).fetch(applied)
        if loaded is None:
            return False
        event, candidate = loaded
        version = int(event["version"])
        with tracer.span("fleet/replica_swap", domain="serve",
                         version=version):
            self._booster.adopt(candidate)
        with self._lock:
            self._applied = version
            self._swaps += 1
            self._last_swap_ts = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        telemetry.count("fleet/replica_swaps")
        telemetry.gauge("fleet/applied_version", version)
        Log.info("fleet: replica adopted published model v%d (%s)",
                 version, event.get("event"))
        return True

    def _worker(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                wait = self._backoff if self._backoff > 0 else self._poll
                self._lock.wait(timeout=wait)
                if self._stopped:
                    return
            try:
                self.poll_once()
                with self._lock:
                    had_backoff = self._backoff > 0
                    self._backoff = 0.0
                if had_backoff:
                    telemetry.gauge("fleet/poll_backoff_ms", 0.0)
            except Exception as exc:
                # a torn read or transient FS/network error must not kill
                # the watcher: count it, back off, retry
                with self._lock:
                    self._errors += 1
                    self._last_error = "%s: %s" % (type(exc).__name__, exc)
                    self._backoff = min(
                        self._backoff_max,
                        (self._backoff if self._backoff > 0
                         else self._poll) * 2.0)
                    backoff = self._backoff
                telemetry.count("fleet/replica_poll_errors")
                telemetry.gauge("fleet/poll_backoff_ms",
                                backoff * 1000.0)
                Log.warning("fleet: replica poll failed (backoff %gs): "
                            "%s: %s", backoff, type(exc).__name__, exc)

    # ------------------------------------------------------------------- state
    @property
    def applied_version(self) -> int:
        with self._lock:
            return self._applied

    def state(self) -> Dict[str, Any]:
        """JSON-serializable watcher state (surfaced on /healthz)."""
        with self._lock:
            return {
                "running": self._thread.is_alive()
                if self._thread is not None else False,
                "applied_version": self._applied,
                "swaps": self._swaps,
                "poll_errors": self._errors,
                "poll_backoff_s": self._backoff,
                "last_error": self._last_error,
                "last_swap_ts": self._last_swap_ts,
                "poll_interval_s": self._poll,
            }

    # ---------------------------------------------------------------- shutdown
    def close(self, timeout: Optional[float] = None) -> None:
        """Stop the poller thread. Idempotent."""
        with self._lock:
            self._stopped = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "ReplicaWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
