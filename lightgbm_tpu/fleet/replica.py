"""Serving replicas: watch the fleet store, hot-swap whole models.

One trainer process publishes promoted models as version-tokened
artifacts (:meth:`~lightgbm_tpu.fleet.store.FleetStore.publish`); each
serving replica runs a :class:`ReplicaWatcher` that polls the store and
adopts newer versions through the existing ``Booster.adopt`` path — the
same single-version-bump atomic swap the in-process online trainer uses,
so every concurrent ``PredictSession`` snapshot on the replica sees the
old ensemble or the new one whole. This is the single-trainer /
many-workers decomposition of arXiv:1611.01276 applied to serving:
replicas never train, they only apply whole historical models.

The store is duck-typed: a filesystem
:class:`~lightgbm_tpu.fleet.store.FleetStore`, a
:class:`~lightgbm_tpu.fleet.transport.RemoteStore` polling one
trainer's ``/fleet`` endpoints over HTTP, or a
:class:`~lightgbm_tpu.fleet.control.MultiEndpointStore` failing over
across a LIST of fleet endpoints (liveness-ranked, capped cooldowns) —
the watcher code is identical in all three: version tokens are global,
so exactly one version bump per applied publish holds no matter which
endpoint served which poll. Loads
go through ``latest_valid_publish``, which verifies each artifact
against the sha256 + length in its publish event and walks back to the
previous good publish past corruption; stale-epoch publishes from a
fenced-off zombie trainer are rejected inside the store scan. A failing
store backs the poll off exponentially (capped, reset on first success)
so a dead store is not hammered at ``poll_interval_s``.

Rollbacks distribute the same way: the trainer publishes the restored
model under a NEW version token, and replicas converge by always
applying the newest token (exactly one local version bump per applied
publish — pinned in tests/test_fleet.py).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..obs import telemetry
from ..obs_trace import tracer
from ..utils.log import LightGBMError, Log

#: per-watcher publish->adopt lag samples kept for heartbeat p50/p99
_LAG_WINDOW = 64


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of an ascending list (None when empty)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def bootstrap_model(store):
    """(booster, version) from the store's newest verified publish, or
    (None, 0) when nothing usable was published yet (the replica then
    needs an ``input_model`` to boot from)."""
    loaded = store.latest_valid_publish(0)
    if loaded is None:
        return None, 0
    event, model_str = loaded
    from ..basic import Booster
    return Booster(model_str=model_str), int(event["version"])


class _ArtifactLoader:
    """Thread-confined model build for one swap: constructed fresh per
    applied publish, so the candidate booster it parses is private to
    that poll (graftlint's thread-reachability stops at a freshly-
    constructed receiver — the online trainer's _CandidateBuilder
    pattern), and the only shared-model call left on the poller thread
    is the lock-guarded ``adopt``."""

    def __init__(self, store) -> None:
        self._store = store

    def fetch(self, min_version: int):
        """(event, candidate booster) for the newest verified publish
        past ``min_version``, or None."""
        loaded = self._store.latest_valid_publish(min_version)
        if loaded is None:
            return None
        event, model_str = loaded
        from ..basic import Booster
        return event, Booster(model_str=model_str)


class ReplicaWatcher:
    """Poll the store for newer published versions and hot-swap them
    into one serving booster.

    ``start=True`` (default) runs a named daemon thread polling every
    ``poll_interval_s``; tests drive :meth:`poll_once` synchronously with
    ``start=False``. Each applied publish is one ``Booster.adopt`` — one
    version bump, whole model, never a partial state. Poll failures back
    off exponentially up to ``backoff_max_s`` (gauge
    ``fleet/poll_backoff_ms``), reset by the next success.
    """

    def __init__(self, booster, store, *,
                 poll_interval_s: float = 0.5,
                 applied_version: int = 0,
                 backoff_max_s: float = 10.0,
                 heartbeat_interval_s: float = 0.0,
                 node_id: Optional[str] = None,
                 role: str = "replica",
                 start: bool = True) -> None:
        if poll_interval_s <= 0:
            raise LightGBMError("fleet poll_interval_s must be > 0, "
                                "got %g" % poll_interval_s)
        if backoff_max_s < poll_interval_s:
            raise LightGBMError("fleet backoff_max_s must be >= "
                                "poll_interval_s, got %g < %g"
                                % (backoff_max_s, poll_interval_s))
        self._booster = booster
        self._store = store
        self._poll = float(poll_interval_s)
        self._backoff_max = float(backoff_max_s)
        # guards the applied-version token, the swap counters and the
        # error-backoff state (the poller thread writes them, /healthz
        # handler threads read), and doubles as the poller's wakeup so
        # close() never waits a full poll interval
        self._lock = threading.Condition()
        self._applied = int(applied_version)
        self._swaps = 0
        self._errors = 0
        self._backoff = 0.0
        self._last_error = ""
        self._last_swap_ts = 0.0
        self._stopped = False
        # convergence observability: newest head version seen on the
        # store, publish->adopt lag of the last swap plus a bounded
        # sample window for heartbeat p50/p99, consecutive poll errors
        # (reset on success — /healthz surfaces "is it failing NOW")
        self._head_version = int(applied_version)
        self._last_adopt_lag_ms: Optional[float] = None
        self._lag_samples: deque = deque(maxlen=_LAG_WINDOW)
        self._consec_errors = 0
        self._node = str(node_id) if node_id else "pid-%d" % os.getpid()
        self._role = str(role)
        self._hb_interval = float(heartbeat_interval_s)
        self._hb_last = 0.0
        self._hb_sent = 0
        self._hb_errors = 0
        telemetry.gauge("fleet/applied_version", self._applied)
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._worker, name="lgbtpu-fleet-replica",
                daemon=True)
            self._thread.start()

    # ----------------------------------------------------------------- polling
    def poll_once(self) -> bool:
        """Check the store once; adopt a newer version if one was
        published. Returns True when a swap happened.

        When serve tracing is on the whole poll runs under a fresh
        trace id — the transport forwards it as ``X-Trace-Id``, so a
        remote adoption shows up in the trainer's recorder under the
        SAME id as the replica's poll/swap spans (one cross-process
        trace in a merged Perfetto load)."""
        if not tracer.serve_on:
            return self._poll_impl()
        with tracer.span("fleet/replica_poll", domain="serve",
                         trace_id=tracer.new_trace_id(),
                         node=self._node):
            return self._poll_impl()

    def _poll_impl(self) -> bool:
        telemetry.count("fleet/replica_polls")
        latest = self._store.latest_publish()
        if latest is None:
            return False
        head = int(latest["version"])
        with self._lock:
            applied = self._applied
            self._head_version = head
        telemetry.gauge("fleet/version_skew", max(0, head - applied))
        if head <= applied:
            return False
        # checksum-verified fetch, falling back past corrupt artifacts;
        # build the private candidate off-lock, then adopt — ONE version
        # bump, whole-model invariant held
        loaded = _ArtifactLoader(self._store).fetch(applied)
        if loaded is None:
            return False
        event, candidate = loaded
        version = int(event["version"])
        with tracer.span("fleet/replica_swap", domain="serve",
                         version=version):
            self._booster.adopt(candidate)
        now = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        # publish->adopt convergence lag: the publish event is stamped
        # with the trainer's wall clock (store._stamp), so the delta is
        # exactly how stale this replica was when it caught up
        ev_ts = float(event.get("ts", 0.0) or 0.0)
        lag_ms = max(0.0, (now - ev_ts) * 1e3) if ev_ts > 0.0 else None
        with self._lock:
            self._applied = version
            self._swaps += 1
            self._last_swap_ts = now
            if lag_ms is not None:
                self._last_adopt_lag_ms = lag_ms
                self._lag_samples.append(lag_ms)
        telemetry.count("fleet/replica_swaps")
        telemetry.gauge("fleet/applied_version", version)
        telemetry.gauge("fleet/version_skew", max(0, head - version))
        if lag_ms is not None:
            telemetry.observe("fleet/publish_adopt_lag_ms", lag_ms)
        Log.info("fleet: replica adopted published model v%d (%s)",
                 version, event.get("event"))
        return True

    def _worker(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                wait = self._backoff if self._backoff > 0 else self._poll
                self._lock.wait(timeout=wait)
                if self._stopped:
                    return
            try:
                self.poll_once()
                with self._lock:
                    had_backoff = self._backoff > 0
                    self._backoff = 0.0
                    self._consec_errors = 0
                if had_backoff:
                    telemetry.gauge("fleet/poll_backoff_ms", 0.0)
            except Exception as exc:
                # a torn read or transient FS/network error must not kill
                # the watcher: count it, back off, retry
                with self._lock:
                    self._errors += 1
                    self._consec_errors += 1
                    self._last_error = "%s: %s" % (type(exc).__name__, exc)
                    self._backoff = min(
                        self._backoff_max,
                        (self._backoff if self._backoff > 0
                         else self._poll) * 2.0)
                    backoff = self._backoff
                telemetry.count("fleet/replica_poll_errors")
                telemetry.gauge("fleet/poll_backoff_ms",
                                backoff * 1000.0)
                Log.warning("fleet: replica poll failed (backoff %gs): "
                            "%s: %s", backoff, type(exc).__name__, exc)
            try:
                self.maybe_heartbeat()
            except Exception:
                # heartbeats are observability: a store that cannot take
                # one must not perturb the poll/backoff loop
                with self._lock:
                    self._hb_errors += 1
                telemetry.count("fleet/heartbeat_errors")

    # -------------------------------------------------------------- heartbeats
    def heartbeat_doc(self) -> Dict[str, Any]:
        """Compact node summary recorded to the store each heartbeat
        (role, version, skew, lag percentiles, key counters) — the unit
        the ``/fleet/status`` rollup federates."""
        with self._lock:
            lags = sorted(self._lag_samples)
            return {
                "node": self._node,
                "role": self._role,
                "pid": os.getpid(),
                "version": self._applied,
                "head_version": self._head_version,
                "skew": max(0, self._head_version - self._applied),
                "swaps": self._swaps,
                "poll_errors": self._errors,
                "consec_poll_errors": self._consec_errors,
                "poll_backoff_s": self._backoff,
                "last_swap_ts": self._last_swap_ts,
                "lag_ms": {
                    "last": self._last_adopt_lag_ms,
                    "p50": _percentile(lags, 0.50),
                    "p99": _percentile(lags, 0.99),
                },
            }

    def maybe_heartbeat(self, force: bool = False) -> bool:
        """Record a heartbeat when one is due (``heartbeat_interval_s``
        elapsed; 0 disables unless ``force``). Duck-tolerant: a store
        without ``record_heartbeat`` is a no-op."""
        if self._hb_interval <= 0 and not force:
            return False
        record = getattr(self._store, "record_heartbeat", None)
        if record is None:
            return False
        now = time.monotonic()  # graftlint: disable=naked-timer -- heartbeat cadence clock, not a measured duration
        with self._lock:
            if not force and now - self._hb_last < self._hb_interval:
                return False
            self._hb_last = now
        if not record(self.heartbeat_doc()):
            return False
        with self._lock:
            self._hb_sent += 1
        return True

    # ------------------------------------------------------------------- state
    @property
    def applied_version(self) -> int:
        with self._lock:
            return self._applied

    def state(self) -> Dict[str, Any]:
        """JSON-serializable watcher state (surfaced on /healthz)."""
        with self._lock:
            return {
                "running": self._thread.is_alive()
                if self._thread is not None else False,
                "node": self._node,
                "role": self._role,
                "applied_version": self._applied,
                "head_version": self._head_version,
                "version_skew": max(0, self._head_version - self._applied),
                "swaps": self._swaps,
                "poll_errors": self._errors,
                "consec_poll_errors": self._consec_errors,
                "poll_backoff_s": self._backoff,
                "last_error": self._last_error,
                "last_swap_ts": self._last_swap_ts,
                "last_adopt_lag_ms": self._last_adopt_lag_ms,
                "poll_interval_s": self._poll,
                "heartbeats": {
                    "interval_s": self._hb_interval,
                    "sent": self._hb_sent,
                    "errors": self._hb_errors,
                },
            }

    # ---------------------------------------------------------------- shutdown
    def close(self, timeout: Optional[float] = None) -> None:
        """Stop the poller thread. Idempotent."""
        with self._lock:
            self._stopped = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "ReplicaWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
