"""LightGBM-TPU: a TPU-native gradient boosting framework.

A brand-new JAX/XLA/Pallas implementation of the LightGBM feature set
(histogram-based leaf-wise GBDT with GOSS/EFB, the full objective/metric zoo,
DART/RF boosting, distributed training over a TPU mesh) — designed TPU-first,
not ported. See SURVEY.md at the repo root for the blueprint.

Public API mirrors the reference python-package:

    import lightgbm_tpu as lgb
    train_set = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary"}, train_set, num_boost_round=100)
    preds = booster.predict(X_test)
"""

__version__ = "0.1.0"

from .config import Config
from .utils.log import Log, LightGBMError
from . import obs

try:  # full API surface; modules come online as the build proceeds
    from .basic import Booster, Dataset, register_logger
    from .engine import train, cv, CVBooster
    from . import serve  # noqa: F401 — lgb.serve.PredictSession et al.
    from . import online  # noqa: F401 — lgb.online.OnlineTrainer et al.
    from .plotting import (  # noqa: F401
        create_tree_digraph,
        plot_importance,
        plot_metric,
        plot_tree,
    )
    from .callback import (
        early_stopping,
        log_evaluation,
        print_evaluation,
        record_evaluation,
        reset_parameter,
        EarlyStopException,
    )
except ImportError:  # pragma: no cover — bootstrap only
    pass

try:  # sklearn wrappers are optional (sklearn itself may be absent)
    from .sklearn import LGBMModel, LGBMClassifier, LGBMRegressor, LGBMRanker
    _SKLEARN = ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
except ImportError:  # pragma: no cover
    _SKLEARN = []

__all__ = [
    "Config",
    "obs",
    "Log",
    "LightGBMError",
    "Dataset",
    "Booster",
    "register_logger",
    "train",
    "cv",
    "CVBooster",
    "early_stopping",
    "log_evaluation",
    "print_evaluation",
    "record_evaluation",
    "reset_parameter",
    "EarlyStopException",
    "plot_importance",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
] + _SKLEARN
