"""Device-cost observability: compiled-executable accounting, live HBM
sampling, and the training health watchdog.

PR 3/7 observability is host-blind to the device: counters and spans say
*when* phases run, not what they *cost* the accelerator. This module adds
the device side, with zero runtime device ops on the measurement paths:

1. **Compile-time cost capture** — every :func:`obs.track_jit` entry point
   reports cache growth here (:func:`on_compile`); the capture re-lowers
   the just-compiled signature through the AOT API and records
   ``Compiled.cost_analysis()`` (FLOPs, bytes accessed) and
   ``Compiled.memory_analysis()`` (argument/output/temp/generated-code
   bytes — the executable's HBM footprint). Lowering after a call hits
   jax's jaxpr cache (sub-ms); the AOT backend compile is the cost, paid
   once per (entry point, signature), and its duration is recorded
   honestly under ``device_cost/capture_s``. The AOT compile's own
   backend event is suppressed so ``jit/backend_compiles`` keeps counting
   only the program's compiles (the compile-budget tests pin that).
2. **Live HBM sampling** — :func:`sample_hbm` reads
   ``device.memory_stats()`` (bytes in use / limit / allocator peak) into
   gauges and keeps a process-wide peak watermark. CPU backends return no
   stats; the sampler degrades to a counted no-op. ``serve`` can run it
   periodically (:func:`start_hbm_sampler`).
3. **Training health watchdog** — :func:`check_finite`
   (``obs_check_finite=off|warn|raise``): one fused device-side
   ``isfinite`` reduction over the grads/scores of a block, fetched as a
   single scalar. ``off`` (the default) never builds a single jnp op —
   the mode check happens in the callers before any array is touched.

Everything lands in the process-global :data:`obs.telemetry` registry, so
it surfaces through ``Booster.telemetry()`` (the ``device_cost`` section
:func:`section` contributes to every snapshot), ``GET /metrics``
Prometheus families (``lgbtpu_device_cost_*``, ``lgbtpu_hbm_*``,
``lgbtpu_obs_nonfinite_*``) and the bench JSON.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, Optional

from .obs import suppress_backend_compiles, telemetry, track_jit
from .utils.log import LightGBMError, Log

#: memory_analysis attributes recorded per captured executable
_MEM_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


class _State:
    """Process-global device-cost aggregates (mirrors the Telemetry
    pattern: one lock, plain dicts, host-only mutation)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.cost_enabled = True     # flipped by configure(obs_device_cost)
        # per tracked-jit name: running sums (flops/bytes accumulate over
        # signatures; HBM fields keep the max — footprints don't add, the
        # executables are not resident simultaneously)
        self.jits: Dict[str, Dict[str, float]] = {}
        self.hbm_peak = 0
        self.hbm_samples = 0
        self.hbm_supported: Optional[bool] = None   # unknown until sampled
        self.hbm_last: Dict[str, int] = {}


_state = _State()  # graftlint: disable=module-mutable-state -- process-global registry, guarded by _state.lock


def configure(cost_enabled: Optional[bool] = None) -> None:
    """Apply config knobs (process-global, last writer wins — same
    contract as obs_trace.tracer.configure)."""
    if cost_enabled is not None:
        with _state.lock:
            _state.cost_enabled = bool(cost_enabled)


def cost_capture_enabled() -> bool:
    with _state.lock:
        return _state.cost_enabled


def reset() -> None:
    """Clear the aggregates (tests, fresh benches). Does not touch the
    enabled flag — reset() between two trains must not change behavior."""
    with _state.lock:
        _state.jits.clear()
        _state.hbm_peak = 0
        _state.hbm_samples = 0
        _state.hbm_supported = None
        _state.hbm_last.clear()


def _first_cost(cost) -> Dict[str, Any]:
    """``Compiled.cost_analysis()`` returns a dict (new jax) or a
    one-element list of dicts (0.4.x); normalize to one dict."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost if isinstance(cost, dict) else {}


def on_compile(name: str, fn, args, kwargs) -> None:
    """Record the device cost of a freshly compiled tracked-jit signature.

    Called by obs._TrackedJit right after it observed cache growth; the
    call's concrete ``args``/``kwargs`` pin the signature, so
    ``fn.lower(*args).compile()`` reproduces the executable that was just
    built. Donated-buffer entry points (the inputs are already consumed)
    and backends without analysis support degrade to a counted error —
    capture must never break training.
    """
    if not cost_capture_enabled():
        return
    t0 = time.perf_counter()   # graftlint: disable=naked-timer -- times a HOST compile, no device work to sync
    try:
        with suppress_backend_compiles():
            compiled = fn.lower(*args, **kwargs).compile()
        cost = _first_cost(compiled.cost_analysis())
        entry = {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed",
                                             cost.get("bytes_accessed", 0.0))),
        }
        try:
            mem = compiled.memory_analysis()
        except Exception:
            mem = None
        for attr, key in _MEM_FIELDS:
            entry[key] = float(getattr(mem, attr, 0) or 0) if mem is not None \
                else 0.0
    except Exception as exc:
        telemetry.count("device_cost/capture_errors")
        Log.debug("device-cost capture failed for %s: %s: %s",
                  name, type(exc).__name__, exc)
        return
    finally:
        telemetry.add_time("device_cost/capture_s",
                           time.perf_counter() - t0)   # graftlint: disable=naked-timer -- host-compile duration
    with _state.lock:
        agg = _state.jits.setdefault(name, {
            "compiles": 0, "flops": 0.0, "bytes_accessed": 0.0,
            "argument_bytes": 0.0, "output_bytes": 0.0, "temp_bytes": 0.0,
            "alias_bytes": 0.0, "generated_code_bytes": 0.0})
        agg["compiles"] += 1
        agg["flops"] += entry["flops"]
        agg["bytes_accessed"] += entry["bytes_accessed"]
        for _, key in _MEM_FIELDS:
            agg[key] = max(agg[key], entry[key])
    telemetry.count("device_cost/captures")
    # Prometheus families: per-jit FLOPs/bytes as counters (accumulate
    # over signatures), HBM footprint as gauges (max over signatures)
    telemetry.count("device_cost/flops/" + name, int(entry["flops"]))
    telemetry.count("device_cost/bytes_accessed/" + name,
                    int(entry["bytes_accessed"]))
    telemetry.gauge("device_cost/temp_hbm_bytes/" + name,
                    int(entry["temp_bytes"]))
    telemetry.gauge("device_cost/argument_hbm_bytes/" + name,
                    int(entry["argument_bytes"]))
    telemetry.gauge("device_cost/output_hbm_bytes/" + name,
                    int(entry["output_bytes"]))
    telemetry.gauge("device_cost/generated_code_bytes/" + name,
                    int(entry["generated_code_bytes"]))
    telemetry.record("device_cost_capture", name=name, **entry)


# ---------------------------------------------------------------------------
# Live HBM sampling
# ---------------------------------------------------------------------------

def sample_hbm() -> Optional[Dict[str, int]]:
    """One ``device.memory_stats()`` sample into gauges + the peak
    watermark. Returns the sample dict, or None on backends without
    memory stats (CPU jax returns None — graceful, counted no-op).
    Host-only: reads allocator state, never touches device queues."""
    stats = None
    try:
        import jax
        devs = jax.local_devices()
        if devs:
            stats = devs[0].memory_stats()
    except Exception:
        stats = None
    if not stats:
        with _state.lock:
            _state.hbm_supported = False
        telemetry.count("obs_device/hbm_sample_noop")
        return None
    in_use = int(stats.get("bytes_in_use", 0))
    limit = int(stats.get("bytes_limit",
                          stats.get("bytes_reservable_limit", 0)))
    alloc_peak = int(stats.get("peak_bytes_in_use", in_use))
    with _state.lock:
        _state.hbm_supported = True
        _state.hbm_samples += 1
        _state.hbm_peak = max(_state.hbm_peak, alloc_peak, in_use)
        peak = _state.hbm_peak
        _state.hbm_last = {"bytes_in_use": in_use, "bytes_limit": limit}
    telemetry.count("obs_device/hbm_samples")
    telemetry.gauge("hbm/bytes_in_use", in_use)
    telemetry.gauge("hbm/peak_bytes", peak)
    if limit:
        telemetry.gauge("hbm/bytes_limit", limit)
    return {"bytes_in_use": in_use, "peak_bytes": peak,
            "bytes_limit": limit}


def maybe_sample_hbm() -> Optional[Dict[str, int]]:
    """Boundary sampler for hot paths (fused block finalize): one stats
    read per call, but once a backend has answered "no memory stats"
    every further call is a single lock-check — the per-block noop
    counter must not grow unbounded on CPU."""
    with _state.lock:
        if _state.hbm_supported is False:
            return None
    return sample_hbm()


def start_hbm_sampler(interval_s: float) -> threading.Event:
    """Sample HBM every ``interval_s`` seconds from a named daemon thread
    until the returned Event is set (``task=serve`` wires this to
    ``obs_hbm_sample_interval_s``). A no-stats backend keeps the thread
    cheap: one failed stats read per tick."""
    stop = threading.Event()

    def _loop():
        while not stop.wait(interval_s):
            sample_hbm()

    t = threading.Thread(target=_loop, name="lgbtpu-hbm-sampler",
                         daemon=True)
    t.start()
    return stop


# ---------------------------------------------------------------------------
# Snapshot section
# ---------------------------------------------------------------------------

def section() -> Dict[str, Any]:
    """The ``device_cost`` section of :meth:`obs.Telemetry.snapshot`:
    per-jit FLOPs/bytes/HBM aggregates plus the HBM watermark. Always
    present (empty ``jits`` when capture is off or nothing compiled) so
    snapshot consumers need no feature detection."""
    with _state.lock:
        jits = {k: dict(v) for k, v in _state.jits.items()}
        hbm: Dict[str, Any] = {
            "supported": _state.hbm_supported,
            "samples": _state.hbm_samples,
            "peak_bytes": _state.hbm_peak,
        }
        hbm.update(_state.hbm_last)
        enabled = _state.cost_enabled
    return {"enabled": enabled, "jits": jits, "hbm": hbm}


def summary() -> Dict[str, Any]:
    """Compact view for ``/healthz``: watermark + totals, no per-jit
    detail (that lives on ``/telemetry`` and ``/metrics``)."""
    with _state.lock:
        return {
            "hbm_supported": _state.hbm_supported,
            "hbm_peak_bytes": _state.hbm_peak,
            "hbm_samples": _state.hbm_samples,
            "captured_jits": len(_state.jits),
            "total_flops": sum(j["flops"] for j in _state.jits.values()),
        }


# ---------------------------------------------------------------------------
# Training health watchdog (obs_check_finite)
# ---------------------------------------------------------------------------

_finite_fn = None  # graftlint: disable=module-mutable-state -- lazily built jit, guarded by _finite_lock
_finite_lock = threading.Lock()


def _nonfinite_counter():
    """The fused device-side reduction: one jitted scalar over all float
    leaves. Built lazily so ``obs_check_finite=off`` never imports a
    kernel, tracked so its compiles are visible in the budget telemetry."""
    global _finite_fn
    with _finite_lock:
        if _finite_fn is None:
            import jax
            import jax.numpy as jnp

            @jax.jit
            def nonfinite(arrays):
                total = jnp.zeros((), jnp.int32)
                for a in arrays:
                    if jnp.issubdtype(a.dtype, jnp.floating):
                        total = total + jnp.sum(~jnp.isfinite(a),
                                                dtype=jnp.int32)
                return total

            _finite_fn = track_jit("obs/check_finite", nonfinite)
        return _finite_fn


def check_finite(kind: str, arrays: Iterable, mode: str) -> int:
    """Count non-finite elements across ``arrays`` on device; count them
    into ``obs/nonfinite_<kind>`` and warn/raise per ``mode``.

    The scalar fetch is an intentional host sync — the watchdog trades
    one 4-byte transfer per block for catching a NaN blow-up at the block
    it happened instead of N iterations later. Callers gate on
    ``mode != "off"`` BEFORE building the argument tuple, so off-mode
    adds zero device ops (pinned by tests/test_obs_device.py against the
    compile-budget harness)."""
    if mode == "off":
        return 0
    arrays = tuple(arrays)
    if not arrays:
        return 0
    n = int(_nonfinite_counter()(arrays))
    telemetry.count("obs/finite_checks")
    if n:
        telemetry.count("obs/nonfinite_" + kind, n)
        msg = ("non-finite values in %s: %d elements (objective blow-up "
               "or bad input; see obs/nonfinite_%s)" % (kind, n, kind))
        if mode == "raise":
            raise LightGBMError(msg)
        Log.warning(msg)
    return n
