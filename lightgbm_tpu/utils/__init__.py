from .log import Log, LightGBMError, verbosity_to_level
from .timer import Timer, global_timer

__all__ = ["Log", "LightGBMError", "verbosity_to_level", "Timer", "global_timer"]
