"""Logging with levels and a redirectable sink.

TPU-native equivalent of the reference's ``Log`` class
(reference: include/LightGBM/utils/log.h:71) with Fatal/Warning/Info/Debug
levels and a thread-local redirect callback (exposed in the reference as
``LGBM_RegisterLogCallback`` / python ``register_logger``).
"""
from __future__ import annotations

import sys
import threading
from typing import Callable, Optional


class LightGBMError(RuntimeError):
    """Error raised by the framework (reference: include/LightGBM/utils/log.h Fatal)."""


_FATAL = -1
_WARNING = 0
_INFO = 1
_DEBUG = 2

_LEVEL_NAMES = {_FATAL: "Fatal", _WARNING: "Warning", _INFO: "Info", _DEBUG: "Debug"}

_state = threading.local()


def _get_level() -> int:
    return getattr(_state, "level", _INFO)


def _get_sink() -> Optional[Callable[[str], None]]:
    return getattr(_state, "sink", None)


class Log:
    """Static-style logger mirroring the reference's API shape."""

    FATAL = _FATAL
    WARNING = _WARNING
    INFO = _INFO
    DEBUG = _DEBUG

    @staticmethod
    def reset_log_level(level: int) -> None:
        _state.level = level

    @staticmethod
    def reset_callback(sink: Optional[Callable[[str], None]]) -> None:
        _state.sink = sink

    @staticmethod
    def _write(level: int, msg: str) -> None:
        if level > _get_level():
            return
        line = "[LightGBM-TPU] [%s] %s" % (_LEVEL_NAMES[level], msg)
        sink = _get_sink()
        if sink is not None:
            sink(line + "\n")
        else:
            print(line, file=sys.stderr, flush=True)

    @staticmethod
    def debug(msg: str, *args) -> None:
        Log._write(_DEBUG, msg % args if args else msg)

    @staticmethod
    def info(msg: str, *args) -> None:
        Log._write(_INFO, msg % args if args else msg)

    @staticmethod
    def warning(msg: str, *args) -> None:
        Log._write(_WARNING, msg % args if args else msg)

    @staticmethod
    def fatal(msg: str, *args) -> None:
        text = msg % args if args else msg
        Log._write(_FATAL, text)
        raise LightGBMError(text)


def verbosity_to_level(verbosity: int) -> int:
    """Map the ``verbosity`` config parameter to a log level.

    Mirrors the reference mapping (src/io/config.cpp:46-56): <0 fatal-only,
    0 warning, 1 info, >1 debug.
    """
    if verbosity < 0:
        return _FATAL
    if verbosity == 0:
        return _WARNING
    if verbosity == 1:
        return _INFO
    return _DEBUG
