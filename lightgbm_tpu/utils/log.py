"""Logging with levels and a redirectable sink.

TPU-native equivalent of the reference's ``Log`` class
(reference: include/LightGBM/utils/log.h:71) with Fatal/Warning/Info/Debug
levels and a thread-local redirect callback (exposed in the reference as
``LGBM_RegisterLogCallback`` / python ``register_logger``).
"""
from __future__ import annotations

import sys
import threading
from typing import Callable, Optional


class LightGBMError(RuntimeError):
    """Error raised by the framework (reference: include/LightGBM/utils/log.h Fatal)."""


_FATAL = -1
_WARNING = 0
_INFO = 1
_DEBUG = 2

_LEVEL_NAMES = {_FATAL: "Fatal", _WARNING: "Warning", _INFO: "Info", _DEBUG: "Debug"}

# The DEFAULT level/sink are process-global: verbosity configured on the
# main thread (train(params={"verbosity": ...}), register_logger) must hold
# in worker threads too — a purely thread-local default silently reverted
# to INFO/stderr inside mesh/multiprocess workers. ``_state`` carries an
# optional per-thread OVERRIDE on top (set_thread_log_level/_sink), used by
# tests and embedders that need one thread quieter than the process.
_default_level: int = _INFO
_default_sink: Optional[Callable[[str], None]] = None

_state = threading.local()


def _get_level() -> int:
    return getattr(_state, "level", _default_level)


def _get_sink() -> Optional[Callable[[str], None]]:
    return getattr(_state, "sink", _default_sink)


def set_thread_log_level(level: Optional[int]) -> None:
    """Per-thread level override; None clears it (falls back to the
    process-global default set by ``Log.reset_log_level``)."""
    if level is None:
        if hasattr(_state, "level"):
            del _state.level
    else:
        _state.level = level


def set_thread_log_sink(sink: Optional[Callable[[str], None]],
                        clear: bool = False) -> None:
    """Per-thread sink override; ``clear=True`` removes the override."""
    if clear:
        if hasattr(_state, "sink"):
            del _state.sink
    else:
        _state.sink = sink


class Log:
    """Static-style logger mirroring the reference's API shape."""

    FATAL = _FATAL
    WARNING = _WARNING
    INFO = _INFO
    DEBUG = _DEBUG

    @staticmethod
    def reset_log_level(level: int) -> None:
        """Set the PROCESS-GLOBAL default level (the reference's
        ResetLogLevel is likewise global); worker threads inherit it.
        Use ``set_thread_log_level`` for a per-thread override."""
        global _default_level
        _default_level = level

    @staticmethod
    def reset_callback(sink: Optional[Callable[[str], None]]) -> None:
        """Set the PROCESS-GLOBAL sink (``register_logger`` semantics:
        one registered logger serves every thread). Use
        ``set_thread_log_sink`` for a per-thread override."""
        global _default_sink
        _default_sink = sink

    @staticmethod
    def _write(level: int, msg: str) -> None:
        if level > _get_level():
            return
        line = "[LightGBM-TPU] [%s] %s" % (_LEVEL_NAMES[level], msg)
        sink = _get_sink()
        if sink is not None:
            sink(line + "\n")
        else:
            print(line, file=sys.stderr, flush=True)

    @staticmethod
    def debug(msg: str, *args) -> None:
        Log._write(_DEBUG, msg % args if args else msg)

    @staticmethod
    def info(msg: str, *args) -> None:
        Log._write(_INFO, msg % args if args else msg)

    @staticmethod
    def warning(msg: str, *args) -> None:
        Log._write(_WARNING, msg % args if args else msg)

    @staticmethod
    def fatal(msg: str, *args) -> None:
        text = msg % args if args else msg
        Log._write(_FATAL, text)
        raise LightGBMError(text)


def verbosity_to_level(verbosity: int) -> int:
    """Map the ``verbosity`` config parameter to a log level.

    Mirrors the reference mapping (src/io/config.cpp:46-56): <0 fatal-only,
    0 warning, 1 info, >1 debug.
    """
    if verbosity < 0:
        return _FATAL
    if verbosity == 0:
        return _WARNING
    if verbosity == 1:
        return _INFO
    return _DEBUG
