"""Named phase timers for tracing/profiling.

TPU-native equivalent of the reference's ``Common::Timer global_timer`` +
RAII ``FunctionTimer`` (reference: include/LightGBM/utils/common.h:931,995),
which accumulates per-phase wall time and prints a report at exit when built
with USE_TIMETAG. Here the report is available programmatically and printed
when ``LIGHTGBM_TPU_TIMETAG=1``.

Note: JAX dispatch is async — timers around jitted calls measure dispatch
unless the caller block_until_ready()s. Use ``timed_sync`` for device phases.
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator


class Timer:
    def __init__(self) -> None:
        self._acc: Dict[str, float] = defaultdict(float)
        self._cnt: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def timed(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] += time.perf_counter() - start
            self._cnt[name] += 1

    def add(self, name: str, seconds: float) -> None:
        self._acc[name] += seconds
        self._cnt[name] += 1

    def maybe_report(self) -> None:
        """Log the accumulated report when profiling is requested
        (LIGHTGBM_TPU_TIMETAG=1 — the reference's USE_TIMETAG analog) or at
        debug verbosity."""
        import os as _os
        from .log import Log as _Log
        if _os.environ.get("LIGHTGBM_TPU_TIMETAG") == "1":
            for line in self.report().splitlines():
                _Log.info("%s", line)
        else:
            for line in self.report().splitlines():
                _Log.debug("%s", line)

    def report(self) -> str:
        lines = ["LightGBM-TPU phase timers:"]
        for name in sorted(self._acc, key=self._acc.get, reverse=True):
            lines.append(
                "  %-40s %10.4f s  (%d calls)" % (name, self._acc[name], self._cnt[name])
            )
        return "\n".join(lines)

    def reset(self) -> None:
        self._acc.clear()
        self._cnt.clear()

    @property
    def times(self) -> Dict[str, float]:
        return dict(self._acc)


global_timer = Timer()


def maybe_print_report() -> None:
    if os.environ.get("LIGHTGBM_TPU_TIMETAG", "0") not in ("0", ""):
        print(global_timer.report())
