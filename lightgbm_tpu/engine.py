"""Training entry points: train() and cv().

Equivalent of the reference python engine (reference:
python-package/lightgbm/engine.py:14 train, cv with _make_n_folds).
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset
from .callback import CallbackEnv, EarlyStopException, early_stopping, log_evaluation
from .config import resolve_aliases
from .obs import telemetry, trace_phase
from .utils.log import Log, LightGBMError


def train(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[List[Dataset]] = None,
    valid_names: Optional[List[str]] = None,
    fobj: Optional[Callable] = None,
    feval: Optional[Callable] = None,
    init_model: Optional[Union[str, Booster]] = None,
    callbacks: Optional[List[Callable]] = None,
    keep_training_booster: bool = True,
) -> Booster:
    """Train a booster (reference: engine.py:14)."""
    params = resolve_aliases(dict(params))
    num_boost_round = int(params.pop("num_iterations", num_boost_round))
    if fobj is not None:
        params.setdefault("objective", "none")
    early_rounds = params.pop("early_stopping_round", 0)

    from .utils.timer import global_timer
    if params.get("machines") or int(params.get("num_machines", 1)) > 1:
        Log.warning(
            "machines/num_machines configure the reference's socket/MPI "
            "cluster; on TPU use jax multi-host instead "
            "(lightgbm_tpu.parallel.distributed.init_distributed + "
            "tree_learner=data)")

    with global_timer.timed("dataset construction"):
        booster = Booster(params, train_set)
    if init_model is not None:
        init = init_model if isinstance(init_model, Booster) else \
            Booster(model_file=init_model)
        # continued training: preload trees + scores. The swap runs under
        # the model lock — a serving session over this booster must never
        # pack a models list that is mid-replacement.
        base = init.model_to_string()
        from .boosting import GBDT
        prev = GBDT.model_from_string(base)
        with booster.inner._cache_lock:
            booster.inner.models = prev.models
            booster.inner.init_scores = prev.init_scores
            booster.inner.iter_ = prev.iter_
        booster.inner._rebuild_scores()

    valid_sets = valid_sets or []
    valid_names = valid_names or []
    for i, vs in enumerate(valid_sets):
        if vs is train_set:
            name = "training"
        else:
            name = valid_names[i] if i < len(valid_names) else "valid_%d" % i
            booster.add_valid(vs, name)

    has_train_in_valid = any(vs is train_set for vs in valid_sets)

    callbacks = list(callbacks or [])
    if early_rounds and int(early_rounds) > 0:
        callbacks.append(early_stopping(int(early_rounds),
                                        first_metric_only=bool(
                                            params.get("first_metric_only", False))))
    verbosity = int(params.get("verbosity", 1))
    auto_callbacks = []
    if verbosity > 0 and not any(getattr(c, "order", None) == 10 for c in callbacks):
        auto_cb = log_evaluation(int(params.get("metric_freq", 1)))
        auto_callbacks.append(auto_cb)
        callbacks.append(auto_cb)
    callbacks_before = [c for c in callbacks if getattr(c, "before_iteration", False)]
    callbacks_after = [c for c in callbacks if not getattr(c, "before_iteration", False)]
    callbacks_before.sort(key=lambda c: getattr(c, "order", 0))
    callbacks_after.sort(key=lambda c: getattr(c, "order", 0))

    begin = booster.inner.iter_
    # fused fast path: no per-iteration observation -> K iters per launch
    # (only the engine's own log_evaluation is inert without valid sets;
    # any user-supplied callback disables fusing)
    user_callbacks = [c for c in callbacks if c not in auto_callbacks]
    if (fobj is None and feval is None and not valid_sets
            and not user_callbacks and booster.inner.supports_fused()):
        block = max(1, int(params.get("tpu_iter_block", 10)))
        end = begin + num_boost_round
        stopped = False
        scheduled = begin  # iter_ lags by the in-flight pipelined block
        try:
            while scheduled < end:
                k = min(block, end - scheduled)
                with global_timer.timed("fused boosting block"), \
                        trace_phase("lgbtpu/train_block"):
                    stopped = booster.inner.train_block(k)
                if stopped:
                    break
                scheduled += k
        except BaseException:
            # best-effort cleanup; never mask the primary error
            try:
                booster.inner.finish_fused("train_error")
            except BaseException:
                pass
            raise
        else:
            # the fused path pipelines host tree reconstruction one block
            # behind the device; finalize the in-flight block
            stopped = booster.inner.finish_fused("train_end") or stopped
        if stopped:
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
        booster.best_iteration = booster.inner.iter_
        # adopt()/restore() update this field from watcher threads under
        # the model lock; take it here too so the field has one guard
        with booster.inner._cache_lock:
            booster.inner.best_iteration = booster.best_iteration
        _ledger_record(booster)
        return booster

    snapshot_freq = int(params.get("snapshot_freq", -1))
    snapshot_base = params.get("output_model") or "model"

    for it in range(begin, begin + num_boost_round):
        for cb in callbacks_before:
            cb(CallbackEnv(booster, params, it, begin,
                           begin + num_boost_round, None, telemetry))
        with global_timer.timed("boosting iteration"), \
                trace_phase("lgbtpu/train_iter"):
            stop = booster.update(fobj=fobj)
        # periodic model snapshots for resume (reference: gbdt.cpp:277
        # SaveModelToFile(model.snapshot_iter_N) every snapshot_freq iters)
        if snapshot_freq > 0 and (it + 1) % snapshot_freq == 0:
            booster.save_model("%s.snapshot_iter_%d" % (snapshot_base, it + 1))
            # snapshots used to drop telemetry; a killed run should leave
            # its counters next to the last model it saved
            dump = str(params.get("dump_telemetry") or "")
            if dump:
                import json
                with open(dump, "w") as f:
                    json.dump(telemetry.snapshot(), f, indent=2)
        evals = []
        with global_timer.timed("metric eval"):
            if has_train_in_valid:
                evals.extend(booster.eval_train(feval))
            evals.extend(booster.eval_valid(feval))
        try:
            for cb in callbacks_after:
                cb(CallbackEnv(booster, params, it, begin,
                               begin + num_boost_round, evals, telemetry))
        except EarlyStopException as e:
            booster.best_iteration = e.best_iteration + 1
            for name, metric, value, _ in e.best_score or []:
                booster.best_score.setdefault(name, {})[metric] = value
            break
        if stop:
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            break
    if booster.best_iteration < 0:
        booster.best_iteration = booster.inner.iter_
    with booster.inner._cache_lock:
        booster.inner.best_iteration = booster.best_iteration
    global_timer.maybe_report()
    _ledger_record(booster)
    return booster


def _ledger_record(booster: Booster) -> None:
    """Append this train run to the JSONL ledger when ``obs_ledger`` is
    on. Zero work (one attribute read) when off; never raises — the run
    it describes already succeeded."""
    try:
        cfg = booster.inner.config
        if not getattr(cfg, "obs_ledger", False):
            return
        ds = booster.inner.train_set
        from . import obs_ledger
        obs_ledger.record_run(cfg, "train", ds.num_data, ds.num_features,
                              extra={"iterations": booster.inner.iter_})
    except Exception as exc:
        Log.warning("ledger record failed (%s): %s", type(exc).__name__, exc)


class CVBooster:  # graftlint: owned -- built and consumed by the cv() caller's thread; never shared with serving workers
    """Ensemble of per-fold boosters (reference: engine.py CVBooster)."""

    def __init__(self) -> None:
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name: str):
        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict[str, Any],
                  seed: int, stratified: bool, shuffle: bool):
    """(reference: engine.py _make_n_folds — stratified / group-aware folds)"""
    binned = full_data.construct(params)
    num_data = binned.num_data
    rng = np.random.RandomState(seed)
    group_info = binned.metadata.query_boundaries
    if group_info is not None:
        # group-wise folds: keep queries intact
        nq = len(group_info) - 1
        q_idx = rng.permutation(nq) if shuffle else np.arange(nq)
        folds_q = np.array_split(q_idx, nfold)
        for fq in folds_q:
            test_rows = np.concatenate(
                [np.arange(group_info[q], group_info[q + 1]) for q in fq]) \
                if len(fq) else np.array([], dtype=np.int64)
            train_rows = np.setdiff1d(np.arange(num_data), test_rows)
            yield train_rows, test_rows
        return
    label = binned.metadata.label
    if stratified and label is not None and len(np.unique(label)) < 50:
        order = []
        for v in np.unique(label):
            idx = np.flatnonzero(label == v)
            if shuffle:
                rng.shuffle(idx)
            order.append(idx)
        # interleave classes, then slice round-robin
        folds = [[] for _ in range(nfold)]
        for idx in order:
            for i, row in enumerate(idx):
                folds[i % nfold].append(row)
        for i in range(nfold):
            test_rows = np.asarray(sorted(folds[i]), dtype=np.int64)
            train_rows = np.setdiff1d(np.arange(num_data), test_rows)
            yield train_rows, test_rows
        return
    idx = rng.permutation(num_data) if shuffle else np.arange(num_data)
    for test_rows in np.array_split(idx, nfold):
        train_rows = np.setdiff1d(np.arange(num_data), test_rows)
        yield np.asarray(train_rows), np.asarray(sorted(test_rows))


def cv(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    nfold: int = 5,
    stratified: bool = True,
    shuffle: bool = True,
    metrics: Optional[Union[str, List[str]]] = None,
    fobj: Optional[Callable] = None,
    feval: Optional[Callable] = None,
    seed: int = 0,
    callbacks: Optional[List[Callable]] = None,
    eval_train_metric: bool = False,
    return_cvbooster: bool = False,
) -> Dict[str, Any]:
    """Cross-validation (reference: engine.py cv)."""
    params = resolve_aliases(dict(params))
    num_boost_round = int(params.pop("num_iterations", num_boost_round))
    if metrics:
        params["metric"] = metrics if isinstance(metrics, list) else [metrics]
    early_rounds = int(params.pop("early_stopping_round", 0) or 0)

    X = train_set.data
    label = train_set.label
    weight = train_set.weight
    group = train_set.group

    import numpy as _np
    Xa = _np.asarray(X, dtype=_np.float64)
    cvb = CVBooster()
    fold_iters = []
    per_fold: List[Dict[str, List[float]]] = []
    for train_rows, test_rows in _make_n_folds(train_set, nfold, params, seed,
                                               stratified, shuffle):
        def subset_group(rows):
            if group is None:
                return None
            qb = train_set.construct(params).metadata.query_boundaries
            qid = np.zeros(len(label), dtype=np.int64)
            for q in range(len(qb) - 1):
                qid[qb[q]:qb[q + 1]] = q
            sub_qid = qid[rows]
            _, sizes = np.unique(sub_qid, return_counts=True)
            return sizes
        tr = Dataset(Xa[train_rows],
                     label=None if label is None else label[train_rows],
                     weight=None if weight is None else weight[train_rows],
                     group=subset_group(train_rows), params=dict(params))
        te = tr.create_valid(Xa[test_rows],
                             label=None if label is None else label[test_rows],
                             weight=None if weight is None else weight[test_rows],
                             group=subset_group(test_rows))
        fold_params = dict(params)
        fold_params["verbosity"] = -1
        if early_rounds:
            fold_params["early_stopping_round"] = early_rounds
        from .callback import record_evaluation
        history: Dict[str, Dict[str, List[float]]] = {}
        bst = train(fold_params, tr, num_boost_round, valid_sets=[te],
                    valid_names=["valid"], fobj=fobj, feval=feval,
                    callbacks=list(callbacks or []) + [record_evaluation(history)])
        cvb.append(bst)
        fold_iters.append(bst.best_iteration)
        per_fold.append(history.get("valid", {}))
    cvb.best_iteration = int(np.min(fold_iters)) if fold_iters else -1

    # aggregate per-iteration metric history across folds
    # (reference cv contract: one list entry per boosting round)
    out: Dict[str, Any] = {}
    metrics_seen = sorted({m for h in per_fold for m in h})
    for metric in metrics_seen:
        series = [h[metric] for h in per_fold if metric in h]
        n_iters = min(len(s) for s in series)
        arr = np.asarray([s[:n_iters] for s in series])
        out["valid %s-mean" % metric] = [float(v) for v in arr.mean(axis=0)]
        out["valid %s-stdv" % metric] = [float(v) for v in arr.std(axis=0)]
    if return_cvbooster:
        out["cvbooster"] = cvb
    return out
