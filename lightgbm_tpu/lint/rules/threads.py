"""Thread rules: lock-discipline and unnamed-thread.

lock-discipline: shared mutable state must have a consistent lock.

The hazard class this encodes is PR 5's: serving threads (the batcher
worker, one stdlib-HTTP handler thread per connection) share Booster and
session state with the training thread. A field written after ``__init__``
and touched from two execution roots needs every access under ONE lock —
or an explicit ``# graftlint: guarded-by=<lock>`` stating why the naked
access is safe (atomic int read, monotonic flag, ...).

Mechanics, on the :mod:`..graph` engine over ``lightgbm_tpu/``:

- **roots**: one per discovered thread entry (``Thread(target=...)``,
  executor ``submit``, HTTP ``do_*`` handler) plus an implicit ``main``
  root covering everything not exclusively thread-internal;
- **shared state**: instance attributes assigned somewhere via
  ``self.<attr> =`` (the engine's attr-owner table), written outside
  init-only methods, and accessed from >= 2 roots. Receivers resolve
  through the engine's types, so ``g._pack_cache`` on a ``GBDT``-typed
  local counts against the same field as ``self._pack_cache``;
- **checked accesses**: every write/mutation anywhere, plus reads in
  thread-reachable functions (a pure read on the main thread of a field
  only threads write is torn-value-safe for the patterns here and stays
  legal). Freshly constructed locals (``C(...)``, ``cls(...)``,
  ``__new__``) are exempt: writes during construction precede sharing;
- **guards**: lexical ``with <x>.<lockattr>:`` blocks where ``lockattr``
  is typed ``threading.Lock/RLock/Condition``; lock identity is the final
  attribute name, so ``with g._cache_lock`` in serve/ matches the
  booster's ``with self._cache_lock``. All checked accesses of one field
  must share at least one lock name.

Two confinement escapes keep the closure honest about ownership (PR 8's
online worker drives ``refit``/``engine.train``, which would otherwise
drag the whole single-threaded training stack into the shared universe):

- **confined call edges**: thread closures stop at method calls on a
  freshly-constructed local (``b = Booster(model_str=s); b.refit(...)``)
  — the receiver is private to the constructing frame, so its class
  surface is thread-local, not shared. Accesses to genuinely shared
  objects must therefore go through ``self``/parameters, which DO
  propagate (see :meth:`~..graph.ProjectGraph.closure`);
- **owned classes**: ``# graftlint: owned`` on a ``class`` line declares
  the ownership-transfer idiom — instances are built and mutated by one
  thread, frozen, then published via an explicitly-locked handoff
  (``Tree`` under ``GBDT.adopt``). Fields of owned classes are exempt;
  the lock rule polices the handoff object instead.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from ..astutil import canonical_call, import_aliases_cached, kwarg_names, \
    own_walk_cached
from ..core import Finding, Project, Rule, SourceFile, register
from ..graph import EXT, FuncInfo, ProjectGraph, graph_for

_LOCK_TYPES = {EXT + "threading.Lock", EXT + "threading.RLock",
               EXT + "threading.Condition", EXT + "threading.Semaphore",
               EXT + "threading.BoundedSemaphore"}

#: container mutations that count as writes. Deliberately excludes
#: queue put/get (SimpleQueue/Queue are internally locked) and Future
#: set_result/set_exception (Future owns its condition).
_MUTATORS = {"append", "extend", "insert", "add", "discard", "remove",
             "clear", "update", "setdefault", "pop", "popitem"}

_GUARDED_RE = re.compile(r"#\s*graftlint:\s*guarded-by=([A-Za-z0-9_.\-]+)")

#: class-line annotation for ownership-transfer types (single-threaded
#: build, locked publish): their instance fields skip lock-discipline
_OWNED_RE = re.compile(r"#\s*graftlint:\s*owned\b")

_READ, _WRITE, _MUTATE = "read", "write", "mutate"


class _Access:
    __slots__ = ("fn", "node", "kind")

    def __init__(self, fn: FuncInfo, node: ast.AST, kind: str) -> None:
        self.fn = fn
        self.node = node
        self.kind = kind


@register
class UnnamedThreadRule(Rule):
    """``threading.Thread`` without ``name=`` shows up as ``Thread-N`` in
    the span flight recorder, ``/telemetry`` thread attribution and stack
    dumps — an anonymous worker is undebuggable once several serve/dump
    threads coexist (obs_trace keys Chrome-trace thread tracks on the
    thread name)."""

    id = "unnamed-thread"
    description = "threading.Thread(...) without a name= (anonymous in " \
                  "span traces and stack dumps)"

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        aliases = import_aliases_cached(f)
        for node in f.walk_nodes():
            if not isinstance(node, ast.Call):
                continue
            if canonical_call(node, aliases) != "threading.Thread":
                continue
            # Thread(group, target, name, ...): a 3rd positional is a name
            if len(node.args) >= 3 or "name" in kwarg_names(node):
                continue
            yield f.finding(node, self.id,
                            "threading.Thread without name= (worker is "
                            "anonymous in span traces and stack dumps)")


@register
class LockDisciplineRule(Rule):
    """Shared mutable state (post-init instance attrs touched from >= 2
    execution roots) must have every access under one consistent lock's
    ``with``-block, or carry ``# graftlint: guarded-by=<lock>``."""

    id = "lock-discipline"
    description = ("shared attr reachable from >=2 thread roots accessed "
                   "outside its lock's with-block")

    def check_project(self, project: Project) -> Iterator[Finding]:
        files = [f for f in project.files
                 if f.tree is not None
                 and f.rel.startswith("lightgbm_tpu/")]
        if not files:
            return
        g = graph_for(project, files, "pkg")
        thread_roots = g.thread_entries()
        if not thread_roots:
            return

        closures: Dict[str, Set[int]] = {}
        in_thread: Set[int] = set()
        target_ids = {id(fn) for fn, _ in thread_roots}
        for fn, label in thread_roots:
            cl = g.closure([fn], confined=False)
            closures.setdefault(label, set()).update(cl)
            in_thread |= cl
        main_closure = g.closure(
            fn for fn in g.funcs if id(fn) not in in_thread)

        owned = {ci.qual for ci in g.classes
                 if _OWNED_RE.search(ci.file.line_text(ci.node.lineno))}
        lock_names = self._lock_names(g)
        init_only = self._init_only(g, target_ids)
        accesses, blessed = self._collect(g, lock_names, init_only)

        for (owner, attr), accs in sorted(accesses.items()):
            if (owner, attr) in blessed or owner in owned:
                continue
            roots: Set[str] = set()
            for a in accs:
                fid = id(a.fn)
                roots.update(lbl for lbl, cl in closures.items()
                             if fid in cl)
                if fid in main_closure:
                    roots.add("main")
            if len(roots) < 2:
                continue
            if not any(a.kind in (_WRITE, _MUTATE) for a in accs):
                continue  # immutable after init: reads need no lock
            checked = [a for a in accs
                       if a.kind in (_WRITE, _MUTATE)
                       or id(a.fn) in in_thread]
            if not checked:
                continue
            helds = [self._held(g, a, lock_names) for a in checked]
            root_list = ", ".join(sorted(roots))
            seen: Set[Tuple[str, int]] = set()
            unguarded = [a for a, h in zip(checked, helds) if not h]
            if unguarded:
                for a in unguarded:
                    key = (a.fn.file.rel, a.node.lineno)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield a.fn.file.finding(
                        a.node, self.id,
                        "%s of shared '%s.%s' (roots: %s) outside a lock; "
                        "guard with its lock's with-block or annotate "
                        "'# graftlint: guarded-by=<lock>'"
                        % (a.kind, owner.rsplit(".", 1)[-1], attr,
                           root_list))
            elif not frozenset.intersection(*helds):
                locks = sorted({n for h in helds for n in h})
                a = checked[0]
                yield a.fn.file.finding(
                    a.node, self.id,
                    "shared '%s.%s' (roots: %s) guarded by no single "
                    "common lock (saw: %s)"
                    % (owner.rsplit(".", 1)[-1], attr, root_list,
                       ", ".join(locks)))

    # ------------------------------------------------------------ lock names
    @staticmethod
    def _lock_names(g: ProjectGraph) -> Set[str]:
        names = {attr for (_cls, attr), ts in g.attr_types.items()
                 if ts & _LOCK_TYPES}
        names |= {name for (_rel, name), ts in g.global_types.items()
                  if ts & _LOCK_TYPES}
        return names

    # ---------------------------------------------------- init-only methods
    @staticmethod
    def _init_only(g: ProjectGraph, target_ids: Set[int]) -> Set[int]:
        """ids of methods whose every caller is (transitively) an
        ``__init__``: writes there happen before the object is shared."""
        callers: Dict[int, List[FuncInfo]] = {}
        for fn in g.funcs:
            for tgt in fn.edges + fn.confined_edges:
                callers.setdefault(id(tgt), []).append(fn)
        init: Set[int] = {id(fn) for fn in g.funcs
                          if fn.is_method and fn.name == "__init__"}
        changed = True
        while changed:
            changed = False
            for fn in g.funcs:
                fid = id(fn)
                if fid in init or not fn.is_method or fid in target_ids:
                    continue
                cs = callers.get(fid)
                if cs and all(id(c) in init for c in cs):
                    init.add(fid)
                    changed = True
        return init

    # ------------------------------------------------------------ collection
    def _collect(self, g: ProjectGraph, lock_names: Set[str],
                 init_only: Set[int]):
        accesses: Dict[Tuple[str, str], List[_Access]] = {}
        blessed: Set[Tuple[str, str]] = set()

        def owner_of(cls_qual: str, attr: str,
                     depth: int = 0) -> Optional[str]:
            """Canonicalize subclass receivers onto the base that assigns
            ``self.<attr>`` (RF accesses land on the GBDT field)."""
            if cls_qual in g.attr_owners.get(attr, ()):
                return cls_qual
            if depth >= 4:
                return None
            ci = g._class_by_qual(cls_qual)
            if ci is None:
                return None
            for b in ci.bases:
                for bc in g.classes_by_name.get(b.rsplit(".", 1)[-1], []):
                    got = owner_of(bc.qual, attr, depth + 1)
                    if got:
                        return got
            return None

        for fn in g.funcs:
            f = fn.file
            env = g._local_env(fn)
            in_init = id(fn) in init_only
            fresh = g.fresh_locals(fn)
            alias: Dict[str, Set[Tuple[str, str]]] = {}

            def recv_keys(expr: ast.AST, attr: str) -> Set[Tuple[str, str]]:
                if isinstance(expr, ast.Name) and expr.id in fresh:
                    return set()
                out: Set[Tuple[str, str]] = set()
                for t in g.expr_type(fn, f, env, expr):
                    if t.startswith(EXT):
                        continue
                    o = owner_of(t, attr)
                    if o:
                        out.add((o, attr))
                return out

            def record(keys: Set[Tuple[str, str]], node: ast.AST,
                       kind: str, is_self: bool) -> None:
                for key in keys:
                    if key[1] in lock_names:
                        continue
                    if in_init and is_self:
                        if kind in (_WRITE, _MUTATE) \
                                and _GUARDED_RE.search(
                                    f.line_text(node.lineno)):
                            blessed.add(key)
                        continue
                    accesses.setdefault(key, []).append(
                        _Access(fn, node, kind))

            def attr_keys(node: ast.Attribute) -> Set[Tuple[str, str]]:
                return recv_keys(node.value, node.attr)

            def is_self(expr: ast.AST) -> bool:
                return isinstance(expr, ast.Name) \
                    and expr.id == fn.self_name

            # pre-pass: one-level aliases (order-free; fresh locals come
            # from the engine — same set the confined-edge cut uses)
            for node in own_walk_cached(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if not names:
                    continue
                v = node.value
                if isinstance(v, ast.Call):
                    vname = v.func
                    if isinstance(vname, ast.Name) \
                            and vname.id == "getattr" \
                            and len(v.args) >= 2 \
                            and isinstance(v.args[1], ast.Constant) \
                            and isinstance(v.args[1].value, str):
                        ks = recv_keys(v.args[0], v.args[1].value)
                        for n in names:
                            alias.setdefault(n, set()).update(ks)
                elif isinstance(v, ast.Attribute):
                    ks = attr_keys(v)
                    for n in names:
                        alias.setdefault(n, set()).update(ks)
                # chained `cache = self._pack_cache = {}`: alias the Name
                # targets to the Attribute targets
                atkeys: Set[Tuple[str, str]] = set()
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        atkeys |= recv_keys(t.value, t.attr)
                for n in names:
                    alias.setdefault(n, set()).update(atkeys)

            # main pass: reads, writes, mutations
            for node in own_walk_cached(fn.node):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute):
                            record(attr_keys(t), node, _WRITE,
                                   is_self(t.value))
                        elif isinstance(t, ast.Subscript):
                            if isinstance(t.value, ast.Attribute):
                                record(attr_keys(t.value), node, _MUTATE,
                                       is_self(t.value.value))
                            elif isinstance(t.value, ast.Name):
                                record(alias.get(t.value.id, set()),
                                       node, _MUTATE, False)
                elif isinstance(node, ast.AugAssign):
                    t = node.target
                    if isinstance(t, ast.Attribute):
                        record(attr_keys(t), node, _WRITE, is_self(t.value))
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Attribute):
                            record(attr_keys(t.value), node, _MUTATE,
                                   is_self(t.value.value))
                elif isinstance(node, ast.Call):
                    fc = node.func
                    if isinstance(fc, ast.Attribute) \
                            and fc.attr in _MUTATORS:
                        base = fc.value
                        if isinstance(base, ast.Attribute):
                            record(attr_keys(base), node, _MUTATE,
                                   is_self(base.value))
                        elif isinstance(base, ast.Name):
                            record(alias.get(base.id, set()), node,
                                   _MUTATE, False)
                    elif isinstance(fc, ast.Name) and fc.id == "getattr" \
                            and len(node.args) >= 2 \
                            and isinstance(node.args[1], ast.Constant) \
                            and isinstance(node.args[1].value, str):
                        record(recv_keys(node.args[0],
                                         node.args[1].value),
                               node, _READ, is_self(node.args[0]))
                elif isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load):
                    record(attr_keys(node), node, _READ, is_self(node.value))
        return accesses, blessed

    # ----------------------------------------------------------- guard state
    def _held(self, g: ProjectGraph, a: _Access,
              lock_names: Set[str]) -> FrozenSet[str]:
        fn = a.fn
        maps = fn.file.__dict__.setdefault("_held_maps", {})
        cache = maps.get(id(fn))
        if cache is None:
            cache = maps[id(fn)] = self._held_map(fn.node, lock_names)
        held = cache.get(id(a.node), frozenset())
        m = _GUARDED_RE.search(a.fn.file.line_text(a.node.lineno))
        if m:
            held = held | {m.group(1).rsplit(".", 1)[-1]}
        return held

    @staticmethod
    def _held_map(fn_node: ast.AST,
                  lock_names: Set[str]) -> Dict[int, FrozenSet[str]]:
        out: Dict[int, FrozenSet[str]] = {}

        def lock_tail(expr: ast.AST) -> Optional[str]:
            while isinstance(expr, ast.Call):
                expr = expr.func  # with self._lock.acquire_timeout(...):
            if isinstance(expr, ast.Attribute):
                return expr.attr if expr.attr in lock_names else None
            if isinstance(expr, ast.Name):
                return expr.id if expr.id in lock_names else None
            return None

        def visit(node: ast.AST, held: FrozenSet[str]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                names = set()
                for item in node.items:
                    tail = lock_tail(item.context_expr)
                    if tail:
                        names.add(tail)
                    visit(item.context_expr, held)
                inner = held | frozenset(names)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                return
            out[id(node)] = held
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for child in ast.iter_child_nodes(fn_node):
            visit(child, frozenset())
        return out
