"""host-sync: no host-device syncs reachable from jit-traced hot phases.

The reachability machinery (symbol table, jit entries, call edges) lived
inside this rule in PR 4; it is now the shared :mod:`..graph` engine, and
this module keeps only the sync-pattern detector and the hot-file scope.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional

from ..astutil import canonical_call, dotted, own_walk_cached
from ..core import Finding, Project, Rule, register
from ..graph import graph_for

#: the traced hot phases: learner/fused drive the per-split loops, ops/
#: holds the kernels, serve/ the resident inference path, fleet/ the
#: replica hot-swap feeding it; obs_device builds the watchdog jit (its
#: scalar fetch is host code by design, but nothing REACHABLE FROM the
#: jit may sync)
HOT_FILES = ("lightgbm_tpu/learner.py", "lightgbm_tpu/fused.py",
             "lightgbm_tpu/obs_device.py")
HOT_DIRS = ("lightgbm_tpu/ops/", "lightgbm_tpu/serve/",
            "lightgbm_tpu/linear/", "lightgbm_tpu/fleet/")

_SYNC_ATTR_CALLS = {"item", "tolist", "block_until_ready"}
_SYNC_DOTTED = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
                "jax.device_get"}
_SYNC_BUILTINS = {"float", "int"}


def hot_subset(project: Project):
    return [f for f in project.files
            if f.tree is not None
            and (f.rel in HOT_FILES or f.rel.startswith(HOT_DIRS))]


@register
class HostSyncRule(Rule):
    """No host-device syncs inside functions reachable from the traced hot
    phases (the round-5 dispatch-soup class: one stray ``.item()`` or
    ``np.asarray`` in the per-split loop serializes the pipeline).

    Reachability comes from the :mod:`..graph` engine built over
    learner.py, fused.py, ops/ and serve/: entries are jit-decorated
    functions and functions wrapped by value in ``jax.jit``/``partial``
    (the learner hands ``partial(build_tree*, ...)`` to jit); edges follow
    bare-name calls (innermost lexical scope first, never methods),
    ``x.attr(...)`` calls (typed receiver first, by-name fallback),
    function-valued arguments (covers ``lax.while_loop``/``scan``/``vmap``
    bodies), and nested defs of hot functions. ``float()``/``int()`` are
    flagged only when the argument visibly involves a jax/jnp call —
    static config scalars stay legal."""

    id = "host-sync"
    description = (".item()/float()/np.asarray/block_until_ready inside "
                   "functions reachable from jit-traced hot phases")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hot_files = hot_subset(project)
        if not hot_files:
            return
        g = graph_for(project, hot_files, "hot")
        hot = g.closure(g.jit_entries())
        for fn in g.funcs:
            if id(fn) not in hot:
                continue
            aliases = g.aliases[fn.file.rel]
            for node in own_walk_cached(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._sync_kind(node, aliases)
                if hit:
                    yield fn.file.finding(
                        node, self.id,
                        "%s in '%s', reachable from a jit-traced hot "
                        "phase (forces a host-device sync)"
                        % (hit, fn.qual))

    @staticmethod
    def _arg_is_arrayish(node: ast.AST, aliases: Dict[str, str]) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                head = canonical_call(n, aliases).split(".")[0]
                if head in ("jax", "jnp") or aliases.get(head) == "jax.numpy":
                    return True
        return False

    @classmethod
    def _sync_kind(cls, node: ast.Call,
                   aliases: Dict[str, str]) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTR_CALLS \
                and not node.args and not node.keywords:
            return ".%s()" % fn.attr
        cname = canonical_call(node, aliases)
        if cname in _SYNC_DOTTED:
            return "%s()" % dotted(node.func)
        if cname in _SYNC_BUILTINS and node.args \
                and cls._arg_is_arrayish(node.args[0], aliases):
            return "%s() conversion" % cname
        return None
