"""tracer-leak: python control flow on jit-traced values.

Inside a jit-reachable function, ``if``/``while``/``assert`` on a value
derived from a traced array either crashes at trace time
(ConcretizationTypeError) or — worse — silently bakes one branch into the
compiled program and retraces on every boundary flip. Shape/dtype/ndim
tests are static and stay legal; concrete host conversions are the
``host-sync`` rule's domain and are not re-reported here.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from ..astutil import canonical_call, own_walk_cached
from ..core import Finding, Project, Rule, register
from ..graph import FuncInfo, graph_for
from .hostsync import hot_subset

#: where findings are reported (serve/ participates in reachability but
#: branches on host numpy there, not tracers)
_REPORT_FILES = ("lightgbm_tpu/learner.py", "lightgbm_tpu/fused.py")
_REPORT_DIRS = ("lightgbm_tpu/ops/",)

#: static attributes of a traced array — branching on them is legal
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "at",
                 "weak_type", "aval"}

#: calls whose result is concrete on the host regardless of the argument
_CONCRETE_CALLS = {"len", "isinstance", "issubclass", "int", "float",
                   "bool", "str", "repr", "getattr", "hasattr", "callable",
                   "type", "id"}
_CONCRETE_METHODS = {"item", "tolist", "keys", "values", "items", "get"}


#: namespaces whose call results are traced arrays. Deliberately narrow:
#: ``jax.default_backend()``/``jax.devices()`` are host calls, and pallas
#: grid/BlockSpec plumbing consumes static shapes, not arrays.
_TRACED_NS = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.scipy.",
              "jax.random.", "jax.ops.")

#: keyword args of jnp calls that carry static config, not array data
_STATIC_KWARGS = {"shape", "dtype", "axis", "num", "size", "length",
                  "total_repeat_length", "num_segments", "precision",
                  "preferred_element_type", "indices_are_sorted",
                  "unique_indices", "mode", "axis_name"}


def _jaxish(cname: str) -> bool:
    return cname.startswith(_TRACED_NS)


def _ordered_stmts(body: List[ast.stmt]) -> Iterator[ast.stmt]:
    for s in body:
        yield s
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        for name in ("body", "orelse", "finalbody"):
            sub = getattr(s, name, None)
            if sub:
                yield from _ordered_stmts(sub)
        for h in getattr(s, "handlers", []) or []:
            yield from _ordered_stmts(h.body)


@register
class TracerLeakRule(Rule):
    """Python ``if``/``while``/``assert`` (or short-circuit ``and``/``or``)
    on a value derived from traced arrays, inside functions reachable from
    a jit entry."""

    id = "tracer-leak"
    description = ("python if/while/assert on a jit-traced value in "
                   "learner.py/fused.py/ops/ hot functions")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hot_files = hot_subset(project)
        if not hot_files:
            return
        g = graph_for(project, hot_files, "hot")
        hot = g.closure(g.jit_entries())
        for fn in g.funcs:
            if id(fn) not in hot:
                continue
            rel = fn.file.rel
            if rel not in _REPORT_FILES \
                    and not rel.startswith(_REPORT_DIRS):
                continue
            yield from self._check_fn(g, fn)

    def _check_fn(self, g, fn: FuncInfo) -> Iterator[Finding]:
        aliases: Dict[str, str] = g.aliases[fn.file.rel]
        params = {a.arg for a in fn.node.args.posonlyargs
                  + fn.node.args.args + fn.node.args.kwonlyargs}
        params.discard(fn.self_name)

        # params count as traced only with array evidence: the param is fed
        # DIRECTLY (not inside a shape tuple or static kwarg) to a jnp/lax
        # call somewhere in this function
        evidence: Set[str] = set()
        for node in own_walk_cached(fn.node):
            if isinstance(node, ast.Call) \
                    and _jaxish(canonical_call(node, aliases)):
                direct = list(node.args) \
                    + [k.value for k in node.keywords
                       if k.arg not in _STATIC_KWARGS]
                for a in direct:
                    if isinstance(a, ast.Name) and a.id in params:
                        evidence.add(a.id)
        taint: Set[str] = set()

        def is_tainted(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in taint or e.id in evidence
            if isinstance(e, ast.Attribute):
                if e.attr in _STATIC_ATTRS:
                    return False
                # attrs of an array-evidence param are config fields of a
                # static struct (hp.max_delta_step), not traced values
                if isinstance(e.value, ast.Name) \
                        and e.value.id in evidence \
                        and e.value.id not in taint:
                    return False
                return is_tainted(e.value)
            if isinstance(e, ast.Subscript):
                return is_tainted(e.value)
            if isinstance(e, ast.Starred):
                return is_tainted(e.value)
            if isinstance(e, ast.Call):
                cname = canonical_call(e, aliases)
                if cname in _CONCRETE_CALLS:
                    return False
                if isinstance(e.func, ast.Attribute):
                    if e.func.attr in _CONCRETE_METHODS \
                            or e.func.attr in _STATIC_ATTRS:
                        return False
                    if _jaxish(cname):
                        return True
                    return is_tainted(e.func.value)
                return _jaxish(cname)
            if isinstance(e, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                    return False
                return is_tainted(e.left) \
                    or any(is_tainted(c) for c in e.comparators)
            if isinstance(e, ast.BinOp):
                return is_tainted(e.left) or is_tainted(e.right)
            if isinstance(e, ast.UnaryOp):
                return is_tainted(e.operand)
            if isinstance(e, ast.BoolOp):
                return any(is_tainted(v) for v in e.values)
            if isinstance(e, ast.IfExp):
                return is_tainted(e.body) or is_tainted(e.orelse)
            if isinstance(e, (ast.Tuple, ast.List)):
                return any(is_tainted(v) for v in e.elts)
            return False

        # propagate through local assignments; two passes cover
        # loop-carried values
        stmts = list(_ordered_stmts(fn.node.body))
        for _ in range(2):
            for s in stmts:
                if isinstance(s, ast.Assign):
                    hit = is_tainted(s.value)
                    for t in s.targets:
                        names = [t] if isinstance(t, ast.Name) else [
                            e for e in getattr(t, "elts", [])
                            if isinstance(e, ast.Name)]
                        for n in names:
                            if hit:
                                taint.add(n.id)
                            else:
                                taint.discard(n.id)
                elif isinstance(s, ast.AugAssign) \
                        and isinstance(s.target, ast.Name):
                    if is_tainted(s.value):
                        taint.add(s.target.id)

        seen: Set[int] = set()
        for s in stmts:
            kind, test = None, None
            if isinstance(s, ast.If):
                kind, test = "if", s.test
            elif isinstance(s, ast.While):
                kind, test = "while", s.test
            elif isinstance(s, ast.Assert):
                kind, test = "assert", s.test
            if test is None or id(test) in seen:
                continue
            seen.add(id(test))
            if is_tainted(test):
                yield fn.file.finding(
                    s, self.id,
                    "python %s on a traced value in jit-reachable '%s' "
                    "(concretizes the tracer; use lax.cond/select or "
                    "hoist the decision to the host)" % (kind, fn.qual))
