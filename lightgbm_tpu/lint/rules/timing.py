"""naked-timer: PERF.md measurement discipline."""
from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import canonical_call, dotted, import_aliases_cached
from ..core import Finding, Rule, SourceFile, register

_TIMER_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.process_time", "time.perf_counter_ns",
                "time.monotonic_ns"}

#: the two modules that IMPLEMENT the trusted-timing discipline
_TIMER_IMPL = {"lightgbm_tpu/obs.py", "lightgbm_tpu/utils/timer.py"}


@register
class NakedTimerRule(Rule):
    """PERF.md measurement discipline: wall clocks must come from
    ``lightgbm_tpu.obs`` (``wall``/``timed_sync`` end in a forced
    1-element transfer; ``block_until_ready`` and bare ``perf_counter``
    pairs do not reliably synchronize through the tunnel)."""

    id = "naked-timer"
    description = ("raw time.time()/perf_counter() wall outside obs.py/"
                   "utils/timer.py; use obs.wall/obs.timed_sync/obs.sync")

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        if f.rel in _TIMER_IMPL:
            return
        aliases = import_aliases_cached(f)
        for node in f.walk_nodes():
            if isinstance(node, ast.Call) \
                    and canonical_call(node, aliases) in _TIMER_CALLS:
                yield f.finding(node, self.id,
                                "naked wall-clock timer %s(); use "
                                "lightgbm_tpu.obs (wall/timed_sync/sync)"
                                % dotted(node.func))
