"""graftlint rule set: this codebase's real hazard classes.

Each rule encodes an invariant that regressed (or nearly regressed) in a
past perf round — see ISSUE 4 / ISSUE 6 / PERF.md. Importing this package
registers every rule via the :func:`~..core.register` decorator;
``scripts/lint.py --list-rules`` prints the table.

Layout (split from the PR 4 single-file ``rules.py`` when the
interprocedural rules landed):

- :mod:`.timing`    — ``naked-timer``
- :mod:`.hostsync`  — ``host-sync`` (on the :mod:`..graph` engine)
- :mod:`.dtypes`    — ``implicit-dtype``, ``dtype-promotion``
- :mod:`.structure` — ``unnamed-pallas-call``, ``mutable-default``,
  ``module-mutable-state``
- :mod:`.threads`   — ``lock-discipline`` (thread roots x shared state),
  ``unnamed-thread`` (every Thread must be name=d for span traces)
- :mod:`.tracer`    — ``tracer-leak`` (python control flow on traced values)
- :mod:`.metricname` — ``metric-name`` (Prometheus family hygiene:
  sanitize-ambiguous names, one family under two types)
- :mod:`.kernels`   — the Pallas kernel contract (ISSUE 19):
  ``pallas-interpret-thread``, ``aliased-ref-read`` (on the engine's
  per-kernel-body ref dataflow), ``recompile-hazard``
- :mod:`.knobs`     — ``knob-contract`` (every ``tpu_*`` knob keeps its
  validation / auto-resolution / bisect-harness / README legs)
"""
from ..astutil import (  # noqa: F401  (re-exported for rule authors/tests)
    canonical_call,
    dotted,
    import_aliases,
)
from . import (  # noqa: F401
    dtypes,
    hostsync,
    kernels,
    knobs,
    metricname,
    structure,
    threads,
    timing,
    tracer,
)
