"""knob-contract: every ``tpu_*`` knob ships its full support surface.

The auto-knob program (ROADMAP: every ``tpu_*`` knob is auto-resolved,
telemetry-recorded and hardware-bisectable) only works while each knob
keeps four legs attached:

1. a **validation clause** in ``config.py`` (``_check`` rejects values
   outside the enum/range — the run ledger's preresolution path replays
   knob values from disk, so unvalidated knobs are an injection seam);
2. an **auto-resolution site** that records the resolved value *with a
   reason string* (``telemetry.record("auto_resolution", ...)`` — the
   reason is what makes a bisect against the ledger actionable);
3. a ``scripts/*_bisect.py`` **harness** that can measure the knob on
   hardware (auto defaults stay "off until the bisect validates it");
4. a **README row** documenting the knob.

Boolean knobs are exempt from (1) (the type is the enum); legs (2) and
(3) apply to *auto* knobs — default ``"auto"`` or resolved through a
recorded auto-resolution. The rule reads sibling files from disk when
they are outside the linted subset (``--changed`` runs), so a partial
lint never reports a leg as missing just because it was not linted.
"""
from __future__ import annotations

import ast
import glob
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Finding, Project, Rule, register

_CONFIG_REL = "lightgbm_tpu/config.py"


def _class_level_knobs(tree: ast.Module) -> List[Tuple[str, ast.AST, int]]:
    out: List[Tuple[str, ast.AST, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and stmt.target.id.startswith("tpu_"):
                out.append((stmt.target.id, stmt.value, stmt.lineno))
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id.startswith("tpu_"):
                        out.append((t.id, stmt.value, stmt.lineno))
    return out


def _package_trees(project: Project) -> Iterator[ast.Module]:
    """ASTs of every ``lightgbm_tpu/*.py`` — parsed files from the lint
    run where available, read from disk otherwise (``--changed``)."""
    seen: Set[str] = set()
    for f in project.files:
        if f.rel.startswith("lightgbm_tpu/") and f.tree is not None:
            seen.add(f.rel)
            yield f.tree
    pkg_root = os.path.join(project.root, "lightgbm_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if not d.startswith((".", "__"))]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            ap = os.path.join(dirpath, fn)
            rel = os.path.relpath(ap, project.root).replace(os.sep, "/")
            if rel in seen:
                continue
            try:
                with open(ap, "r", encoding="utf-8",
                          errors="replace") as fh:
                    yield ast.parse(fh.read(), filename=rel)
            except (OSError, SyntaxError):
                continue


def _bisect_sources(project: Project) -> Iterator[str]:
    seen: Set[str] = set()
    for f in project.files:
        if f.rel.startswith("scripts/") and f.rel.endswith("_bisect.py"):
            seen.add(f.rel)
            yield f.source
    for ap in sorted(glob.glob(os.path.join(project.root, "scripts",
                                            "*_bisect.py"))):
        rel = os.path.relpath(ap, project.root).replace(os.sep, "/")
        if rel in seen:
            continue
        try:
            with open(ap, "r", encoding="utf-8", errors="replace") as fh:
                yield fh.read()
        except OSError:
            continue


def _resolution_sites(tree: ast.Module) -> Dict[str, bool]:
    """knob name -> "records a non-empty reason" for every
    auto-resolution site in one module: direct
    ``telemetry.record("auto_resolution", ..., knob=..., reason=...)``
    calls plus calls through local recorder helpers (the learner's
    ``_rec(knob, value, reason)`` pattern) whose body does the record."""
    recorders: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in ast.walk(node):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "record" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and n.args[0].value == "auto_resolution":
                recorders.add(node.name)
                break
    out: Dict[str, bool] = {}

    def reason_ok(expr: Optional[ast.AST]) -> bool:
        if expr is None:
            return False
        if isinstance(expr, ast.Constant):
            return bool(expr.value)
        return True  # dynamically built reason: trust it

    def note(knob: Optional[ast.AST], reason: Optional[ast.AST]) -> None:
        if isinstance(knob, ast.Constant) and isinstance(knob.value, str):
            out[knob.value] = out.get(knob.value, False) or reason_ok(reason)

    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        kws = {k.arg: k.value for k in n.keywords if k.arg is not None}
        if isinstance(n.func, ast.Name) and n.func.id in recorders:
            note(n.args[0] if n.args else None,
                 n.args[2] if len(n.args) >= 3 else kws.get("reason"))
        elif isinstance(n.func, ast.Attribute) and n.func.attr == "record" \
                and n.args and isinstance(n.args[0], ast.Constant) \
                and n.args[0].value == "auto_resolution":
            note(kws.get("knob"), kws.get("reason"))
    return out


@register
class KnobContractRule(Rule):
    """Cross-file contract check over the ``tpu_*`` knob surface (see
    module docstring for the four legs)."""

    id = "knob-contract"
    description = ("every tpu_* knob in config.py needs a validation "
                   "clause, a README row, and (for auto knobs) a "
                   "reasoned auto-resolution site plus a "
                   "scripts/*_bisect.py harness")

    def check_project(self, project: Project) -> Iterator[Finding]:
        cfg = project.by_rel(_CONFIG_REL)
        if cfg is None or cfg.tree is None:
            return
        knobs = _class_level_knobs(cfg.tree)
        if not knobs:
            return

        cfg_attr_refs = {n.attr for n in cfg.walk_nodes()
                         if isinstance(n, ast.Attribute)}
        resolved: Dict[str, bool] = {}
        for tree in _package_trees(project):
            for knob, ok in _resolution_sites(tree).items():
                resolved[knob] = resolved.get(knob, False) or ok
        bisect_text = "\n".join(_bisect_sources(project))
        readme_text: Optional[str] = None
        readme_path = os.path.join(project.root, "README.md")
        if os.path.exists(readme_path):
            with open(readme_path, "r", encoding="utf-8",
                      errors="replace") as fh:
                readme_text = fh.read()

        for knob, default, lineno in knobs:
            is_bool = isinstance(default, ast.Constant) \
                and isinstance(default.value, bool)
            is_auto = (isinstance(default, ast.Constant)
                       and default.value == "auto") or knob in resolved
            if not is_bool and knob not in cfg_attr_refs:
                yield cfg.finding(
                    lineno, self.id,
                    "%s has no validation clause in config.py — _check "
                    "must reject out-of-range values (the run-ledger "
                    "preresolution path replays knobs from disk)" % knob)
            if readme_text is not None and knob not in readme_text:
                yield cfg.finding(
                    lineno, self.id,
                    "%s has no README row — every tpu_* knob is "
                    "documented in the knob table" % knob)
            if is_auto:
                if knob not in resolved:
                    yield cfg.finding(
                        lineno, self.id,
                        "auto knob %s has no auto-resolution site "
                        "recording telemetry('auto_resolution', ...) "
                        "with a reason" % knob)
                elif not resolved[knob]:
                    yield cfg.finding(
                        lineno, self.id,
                        "auto knob %s's auto-resolution site records "
                        "no reason string — unreasoned resolutions "
                        "make ledger bisects unactionable" % knob)
                if knob not in bisect_text:
                    yield cfg.finding(
                        lineno, self.id,
                        "auto knob %s has no scripts/*_bisect.py "
                        "harness mentioning it — auto defaults stay "
                        "off until a bisect validates them on "
                        "hardware" % knob)
