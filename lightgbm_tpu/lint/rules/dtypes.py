"""implicit-dtype and dtype-promotion: explicit, stable dtypes in ops/.

``implicit-dtype`` (PR 4) forces constructors to spell their dtype out.
``dtype-promotion`` (ISSUE 6) goes further: it propagates the declared
dtypes through local dataflow and flags the two promotions that actually
cost on this hardware — f32 meeting f64 (silent 2x widening of a kernel
intermediate) and i32 meeting i64 (indices leaving the fast lane). Python
literals are weak-typed and adopt the array's dtype, so ``x * 0.5`` on an
f32 array stays clean.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from ..astutil import (canonical_call, dotted, import_aliases_cached,
                       kwarg_names, own_walk)
from ..core import Finding, Rule, SourceFile, register

#: constructor -> index of the positional dtype parameter
_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "arange": 3,
              "asarray": 1}
_JNP_HEADS = {"jax.numpy", "jnp"}

#: kernel directories both dtype rules police (linear/ holds the batched
#: leaf-solve and coefficient-table kernels — same MXU discipline as ops/)
_KERNEL_DIRS = ("lightgbm_tpu/ops/", "lightgbm_tpu/linear/")


@register
class ImplicitDtypeRule(Rule):
    """ops/ kernels must spell dtypes out: implicit f32/i32 promotion
    changed bit patterns across jax versions and hid u8-vs-i32 traffic
    regressions; golden/consistency tests pin the explicit choice."""

    id = "implicit-dtype"
    description = ("jnp.zeros/ones/empty/full/arange/asarray without an "
                   "explicit dtype in lightgbm_tpu/ops/ kernels")

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        if not f.rel.startswith(_KERNEL_DIRS):
            return
        aliases = import_aliases_cached(f)
        for node in f.walk_nodes():
            if not isinstance(node, ast.Call):
                continue
            cname = canonical_call(node, aliases)
            head, _, tail = cname.rpartition(".")
            if head not in _JNP_HEADS and aliases.get(head, head) != "jax.numpy":
                continue
            pos = _DTYPE_POS.get(tail)
            if pos is None:
                continue
            if "dtype" in kwarg_names(node) or len(node.args) > pos:
                continue
            yield f.finding(node, self.id,
                            "%s without an explicit dtype" % dotted(node.func))


# ---------------------------------------------------------------------------
# dtype-promotion
# ---------------------------------------------------------------------------

_FLOATS = {"float16": 16, "bfloat16": 16, "float32": 32, "float64": 64}
_INTS = {"int8": 8, "int16": 16, "int32": 32, "int64": 64,
         "uint8": 8, "uint16": 16, "uint32": 32, "uint64": 64}
_KNOWN = set(_FLOATS) | set(_INTS) | {"bool_", "bool"}

#: variadic jnp families where argument dtypes meet
_MEET_CALLS = {"add", "subtract", "multiply", "divide", "where",
               "concatenate", "stack", "hstack", "vstack", "dot", "matmul",
               "maximum", "minimum", "mod", "remainder", "equal",
               "not_equal", "less", "greater", "less_equal",
               "greater_equal"}
#: pure-passthrough jnp calls: result dtype == first array argument's
_PASS_CALLS = {"sum", "mean", "reshape", "transpose", "squeeze",
               "expand_dims", "cumsum", "sort", "flip", "roll", "take",
               "abs", "negative", "clip", "pad", "ravel", "broadcast_to",
               "max", "min"}
_PASS_METHODS = {"sum", "mean", "reshape", "transpose", "squeeze", "ravel",
                 "cumsum", "sort", "clip", "copy", "T", "max", "min"}
#: index consumers: (callee tail, index argument position)
_INDEX_CALLS = {"take": 1, "take_along_axis": 1, "bincount": 0,
                "segment_sum": 1}


def _family(d: str) -> Optional[str]:
    if d in _FLOATS:
        return "float"
    if d in _INTS:
        return "int"
    return None


def _width(d: str) -> int:
    return _FLOATS.get(d) or _INTS.get(d) or 0


@register
class DtypePromotionRule(Rule):
    """Propagate declared dtypes through ops/ kernels and flag f32/f64
    meets, i32/i64 meets, and int64 values used as indices. This retires
    the manual implicit-dtype audit from PERF.md: the declared dtype is
    now checked at every point of use, not just at construction."""

    id = "dtype-promotion"
    description = ("f32/f64 or i32/i64 dtype meet (silent widening) or "
                   "int64 indexing in lightgbm_tpu/ops/ kernels")

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        if not f.rel.startswith(_KERNEL_DIRS):
            return
        aliases = import_aliases_cached(f)
        # module-level declared constants participate
        genv = self._scan_block(None, f, aliases, f.tree.body, {}, None)
        for node in f.walk_nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                found: Dict[Tuple[int, int], Finding] = {}
                env = dict(genv)
                # two passes: loop-carried vars get their dtype on round 2
                for _ in range(2):
                    env = self._scan_block(node, f, aliases, node.body,
                                           env, found)
                yield from found.values()

    # ------------------------------------------------------------- dtype eval
    def _dtype_expr(self, e: ast.AST, aliases: Dict[str, str]
                    ) -> Optional[str]:
        """A dtype ANNOTATION expression -> canonical name ('float32')."""
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            return e.value if e.value in _KNOWN else None
        name = dotted(e)
        if name:
            tail = name.rsplit(".", 1)[-1]
            if tail in _KNOWN:
                return tail
        if isinstance(e, ast.Call):  # jnp.dtype("float32") etc.
            if e.args:
                return self._dtype_expr(e.args[0], aliases)
        return None

    def _is_jnp(self, cname: str, aliases: Dict[str, str]) -> bool:
        head, _, _tail = cname.rpartition(".")
        return head in _JNP_HEADS or aliases.get(head, head) == "jax.numpy" \
            or head == "jax.numpy"

    def _value_dtype(self, e: ast.AST, env: Dict[str, str],
                     aliases: Dict[str, str],
                     report) -> Optional[str]:
        """Abstract dtype of a VALUE expression; None = unknown/weak."""
        if isinstance(e, ast.Name):
            return env.get(e.id)
        if isinstance(e, ast.Subscript):
            return self._value_dtype(e.value, env, aliases, report)
        if isinstance(e, ast.Attribute):
            if e.attr == "T":
                return self._value_dtype(e.value, env, aliases, report)
            return None
        if isinstance(e, ast.UnaryOp):
            return self._value_dtype(e.operand, env, aliases, report)
        if isinstance(e, ast.BinOp):
            lt = self._value_dtype(e.left, env, aliases, report)
            rt = self._value_dtype(e.right, env, aliases, report)
            return self._meet(lt, rt, e, report)
        if isinstance(e, ast.IfExp):
            lt = self._value_dtype(e.body, env, aliases, report)
            rt = self._value_dtype(e.orelse, env, aliases, report)
            return self._meet(lt, rt, e, report)
        if isinstance(e, ast.Compare):
            ds = [self._value_dtype(e.left, env, aliases, report)]
            ds += [self._value_dtype(c, env, aliases, report)
                   for c in e.comparators]
            out = None
            for d in ds:
                out = self._meet(out, d, e, report)
            return "bool_"
        if isinstance(e, ast.Call):
            return self._call_dtype(e, env, aliases, report)
        return None

    def _call_dtype(self, e: ast.Call, env: Dict[str, str],
                    aliases: Dict[str, str], report) -> Optional[str]:
        fc = e.func
        # x.astype(D) / method passthrough
        if isinstance(fc, ast.Attribute):
            if fc.attr == "astype" and e.args:
                return self._dtype_expr(e.args[0], aliases)
            if fc.attr in _PASS_METHODS:
                return self._value_dtype(fc.value, env, aliases, report)
        cname = canonical_call(e, aliases)
        if not cname or not self._is_jnp(cname, aliases):
            return None
        tail = cname.rsplit(".", 1)[-1]
        # explicit dtype argument wins
        for kw in e.keywords:
            if kw.arg == "dtype":
                return self._dtype_expr(kw.value, aliases)
        pos = _DTYPE_POS.get(tail)
        if pos is not None and len(e.args) > pos:
            d = self._dtype_expr(e.args[pos], aliases)
            if d:
                return d
        if tail in _KNOWN and e.args:  # jnp.float32(x) cast
            return tail
        # index consumers: flag int64 indices
        ipos = _INDEX_CALLS.get(tail)
        if ipos is not None and len(e.args) > ipos:
            d = self._value_dtype(e.args[ipos], env, aliases, report)
            if d == "int64" and report is not None:
                report(e, "int64 indices into jnp.%s (indices should stay "
                          "int32 on this hardware)" % tail)
        if tail in _MEET_CALLS:
            out = None
            skip = 1 if tail == "where" else 0  # condition arg is bool
            for i, a in enumerate(e.args):
                if i < skip:
                    continue
                if isinstance(a, (ast.List, ast.Tuple)):
                    for el in a.elts:
                        out = self._meet(out, self._value_dtype(
                            el, env, aliases, report), e, report)
                else:
                    out = self._meet(out, self._value_dtype(
                        a, env, aliases, report), e, report)
            if tail in ("equal", "not_equal", "less", "greater",
                        "less_equal", "greater_equal"):
                return "bool_"
            return out
        if tail in _PASS_CALLS and e.args:
            return self._value_dtype(e.args[0], env, aliases, report)
        return None

    def _meet(self, a: Optional[str], b: Optional[str], node: ast.AST,
              report) -> Optional[str]:
        if a is None:
            return b
        if b is None or a == b:
            return a
        fa, fb = _family(a), _family(b)
        if fa == fb and fa is not None and _width(a) != _width(b):
            wide, narrow = (a, b) if _width(a) > _width(b) else (b, a)
            if {a, b} == {"float32", "float64"} \
                    or (fa == "int" and {_width(a), _width(b)} == {32, 64}):
                if report is not None:
                    report(node, "%s meets %s (silent promotion to %s; "
                                 "align dtypes explicitly)"
                           % (narrow, wide, wide))
            return wide
        if fa == "float":
            return a
        if fb == "float":
            return b
        return None

    # ---------------------------------------------------------------- driver
    def _scan_block(self, fn_node, f: SourceFile, aliases: Dict[str, str],
                    body: List[ast.stmt], env: Dict[str, str],
                    found: Optional[Dict[Tuple[int, int], Finding]]
                    ) -> Dict[str, str]:
        def report(node: ast.AST, msg: str) -> None:
            if found is None:
                return
            key = (node.lineno, node.col_offset)
            if key not in found:
                found[key] = f.finding(node, self.id, msg)

        rpt = report if found is not None else None

        def stmts(block: List[ast.stmt]) -> None:
            for s in block:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                if isinstance(s, ast.Assign):
                    d = self._value_dtype(s.value, env, aliases, rpt)
                    for t in s.targets:
                        if isinstance(t, ast.Name):
                            if d:
                                env[t.id] = d
                            else:
                                env.pop(t.id, None)
                elif isinstance(s, ast.AnnAssign) and s.value is not None \
                        and isinstance(s.target, ast.Name):
                    d = self._value_dtype(s.value, env, aliases, rpt)
                    if d:
                        env[s.target.id] = d
                elif isinstance(s, ast.AugAssign) \
                        and isinstance(s.target, ast.Name):
                    lt = env.get(s.target.id)
                    rt = self._value_dtype(s.value, env, aliases, rpt)
                    d = self._meet(lt, rt, s, rpt)
                    if d:
                        env[s.target.id] = d
                elif isinstance(s, (ast.Expr, ast.Return)):
                    if s.value is not None:
                        self._value_dtype(s.value, env, aliases, rpt)
                for name in ("body", "orelse", "finalbody"):
                    sub = getattr(s, name, None)
                    if sub and not isinstance(s, (ast.FunctionDef,
                                                  ast.AsyncFunctionDef,
                                                  ast.ClassDef)):
                        stmts(sub)
                for h in getattr(s, "handlers", []) or []:
                    stmts(h.body)
                # visit tests/iters for index findings
                for name in ("test", "iter"):
                    sub = getattr(s, name, None)
                    if sub is not None:
                        self._value_dtype(sub, env, aliases, rpt)

        stmts(body)
        return env
