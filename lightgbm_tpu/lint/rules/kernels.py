"""Pallas kernel-contract rules (ISSUE 19 tentpole).

PR 17 hand-fixed two *latent* kernel bugs that every existing rule
missed: a ``pallas_call`` that never threaded ``interpret=`` (so the
CPU parity oracle silently compiled for a backend it could not have),
and an RMW drain tile that read an ``input_output_aliases``-aliased
input ref after the output had been written — correct on TPU where the
alias is in-place, stale under the interpreter where input and output
are distinct buffers. Both bug shapes are now rules, plus the
zero-recompile invariant the whole perf program rests on:

- :class:`PallasInterpretThreadRule` — ``interpret=`` must be present
  and must dataflow from a parameter or config, never a literal;
- :class:`AliasedRefReadRule` — no input-ref read after the first
  aliased-output write, on the engine's new per-kernel-body ref
  dataflow (:meth:`~..graph.ProjectGraph.ref_events`);
- :class:`RecompileHazardRule` — host-dynamic values (``.item()``,
  ``int()`` of traced arrays, ``np.asarray`` of device arrays) flowing
  into shape positions (``jnp.zeros``/``reshape``, ``grid=``,
  ``BlockSpec``, ``lax.dynamic_slice`` sizes) in the hot modules.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..astutil import canonical_call, dotted, own_walk_cached
from ..core import Finding, Project, Rule, SourceFile, register
from ..graph import PARTIAL_HEADS, FuncInfo, ProjectGraph, graph_for
from .hostsync import HostSyncRule, hot_subset

_PKG = "lightgbm_tpu/"


def _is_pallas_call(node: ast.Call) -> bool:
    return dotted(node.func).rsplit(".", 1)[-1] == "pallas_call"


def _pkg_subset(project: Project):
    return [f for f in project.files
            if f.tree is not None and f.rel.startswith(_PKG)]


# ---------------------------------------------------------------------------
# pallas-interpret-thread
# ---------------------------------------------------------------------------

@register
class PallasInterpretThreadRule(Rule):
    """Every ``pl.pallas_call`` in ``lightgbm_tpu/`` must receive an
    ``interpret=`` kwarg that dataflows from a caller parameter or a
    config binding — never omitted (the call silently picks the compiled
    path and the CPU parity oracle stops covering the kernel, PR 17 bug
    #1) and never a literal (a hardwired ``interpret=False`` pins the
    kernel to Mosaic on hosts that do not have it). Perf-harness scripts
    under ``scripts/`` stay free to hardwire the mode."""

    id = "pallas-interpret-thread"
    description = ("pl.pallas_call in lightgbm_tpu/ must thread "
                   "interpret= from a parameter or config, not omit it "
                   "or pass a literal")

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        if not f.rel.startswith(_PKG) or f.tree is None:
            return
        yield from self._visit(f, f.tree, [])

    def _visit(self, f: SourceFile, node: ast.AST,
               fstack: List[ast.AST]) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._visit(f, child, fstack + [child])
                continue
            if isinstance(child, ast.Call) and _is_pallas_call(child):
                yield from self._check_call(f, child, fstack)
            yield from self._visit(f, child, fstack)

    def _check_call(self, f: SourceFile, node: ast.Call,
                    fstack: List[ast.AST]) -> Iterator[Finding]:
        kw = next((k for k in node.keywords if k.arg == "interpret"), None)
        if kw is None:
            # a **kwargs splat may carry interpret= — can't see through it
            if any(k.arg is None for k in node.keywords):
                return
            yield f.finding(
                node, self.id,
                "pallas_call without interpret=: the kernel always "
                "compiles and the CPU parity oracle never covers it "
                "(thread a parameter or a config flag)")
            return
        try:
            ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            pass
        else:
            yield f.finding(
                kw.value, self.id,
                "interpret= is a literal: thread it from a caller "
                "parameter or config so the parity oracle can flip it")
            return
        if isinstance(kw.value, ast.Name):
            yield from self._check_name(f, kw.value, fstack)

    def _check_name(self, f: SourceFile, name: ast.Name,
                    fstack: List[ast.AST]) -> Iterator[Finding]:
        for fn in fstack:
            a = fn.args
            params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
            if name.id in params:
                return  # threads from a caller parameter
        assigns: List[ast.AST] = []
        for scope in [f.tree] + fstack:
            for n in own_walk_cached(scope):
                if isinstance(n, ast.Assign):
                    if any(isinstance(t, ast.Name) and t.id == name.id
                           for t in n.targets):
                        assigns.append(n.value)
                elif isinstance(n, ast.AnnAssign) and n.value is not None \
                        and isinstance(n.target, ast.Name) \
                        and n.target.id == name.id:
                    assigns.append(n.value)
        if not assigns:
            return  # imported config (e.g. ``from .partition import _INTERPRET``)
        literal = True
        for v in assigns:
            try:
                ast.literal_eval(v)
            except (ValueError, SyntaxError):
                literal = False
                break
        if literal:
            yield f.finding(
                name, self.id,
                "interpret=%s is bound only to literals — a laundered "
                "constant; thread it from a parameter or config" % name.id)


# ---------------------------------------------------------------------------
# aliased-ref-read
# ---------------------------------------------------------------------------

@register
class AliasedRefReadRule(Rule):
    """With ``input_output_aliases={i: j}`` the aliased input and output
    are ONE buffer on TPU but TWO buffers under ``interpret=True`` — so
    a kernel body that reads input ref *i* after the first write to
    output ref *j* sees fresh data compiled and stale data interpreted
    (PR 17 bug #2: the RMW drain tile read ``work_in`` where it had to
    re-read ``work_ref``). Events come from the engine's per-kernel-body
    ref dataflow; reads of regions the output never wrote (a different
    leading plane) stay legal."""

    id = "aliased-ref-read"
    description = ("kernel reads an input_output_aliases input ref "
                   "after the aliased output was written (stale under "
                   "interpret=True)")

    def check_project(self, project: Project) -> Iterator[Finding]:
        files = _pkg_subset(project)
        if not files:
            return
        g = graph_for(project, files, "pkg")
        scopes = []  # (owner FuncInfo or None, call nodes, SourceFile)
        for f in files:
            scopes.append((None, [n for n in own_walk_cached(f.tree)
                                  if isinstance(n, ast.Call)], f))
        for fn in g.funcs:
            scopes.append((fn, g._fn_facts[id(fn)][3], fn.file))
        for owner, calls, f in scopes:
            for node in calls:
                if not isinstance(node.func, ast.Call) \
                        or not _is_pallas_call(node.func):
                    continue
                yield from self._check_site(g, owner, f, node)

    def _check_site(self, g: ProjectGraph, owner: Optional[FuncInfo],
                    f, outer: ast.Call) -> Iterator[Finding]:
        inner = outer.func
        aliases_kw = next((k.value for k in inner.keywords
                           if k.arg == "input_output_aliases"), None)
        if not isinstance(aliases_kw, ast.Dict):
            return
        pairs: List[Tuple[int, int]] = []
        for k, v in zip(aliases_kw.keys, aliases_kw.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, int) \
                    and isinstance(v, ast.Constant) \
                    and isinstance(v.value, int):
                pairs.append((k.value, v.value))
        if not pairs or not inner.args:
            return
        if any(isinstance(a, ast.Starred) for a in outer.args):
            return
        resolved = self._resolve_kernel(g, owner, f, inner.args[0])
        if resolved is None:
            return
        kern, offset = resolved
        if kern.node.args.vararg is not None:
            return  # runtime-dependent unpacking: not analyzable
        params = [a.arg for a in kern.node.args.posonlyargs
                  + kern.node.args.args][offset:]
        num_inputs = len(outer.args)
        for i, j in pairs:
            if i >= num_inputs or num_inputs + j >= len(params):
                continue
            in_p, out_p = params[i], params[num_inputs + j]
            events = g.ref_events(kern, {in_p: in_p, out_p: out_p})
            written = False
            labels: Set[Optional[str]] = set()
            for ev in events:
                if ev.ref == out_p and ev.kind == "write":
                    written = True
                    labels.add(ev.label)
                elif ev.ref == in_p and ev.kind == "read" and written \
                        and (ev.label is None or None in labels
                             or ev.label in labels):
                    yield ev.file.finding(
                        ev.node, self.id,
                        "kernel '%s' reads aliased input ref '%s' after "
                        "writing aliased output ref '%s' "
                        "(input_output_aliases={%d: %d} at %s:%d) — "
                        "stale under interpret=True; re-read through "
                        "'%s'" % (kern.qual, in_p, out_p, i, j, f.rel,
                                  inner.lineno, out_p))
                    break

    @staticmethod
    def _resolve_kernel(g: ProjectGraph, owner: Optional[FuncInfo], f,
                        expr: ast.AST) -> Optional[Tuple[FuncInfo, int]]:
        """``pallas_call``'s first argument to a (FuncInfo, positional
        offset): a bare function name, a ``partial(fn, ...)`` call, or a
        local bound to either. The offset counts positional args a
        partial pre-binds (they shift the ref parameters right)."""
        for _hop in range(3):
            if isinstance(expr, ast.Call):
                cname = dotted(expr.func)
                if not (cname in PARTIAL_HEADS
                        or cname.endswith(".partial")) or not expr.args:
                    return None
                offset = len(expr.args) - 1
                expr = expr.args[0]
                if isinstance(expr, ast.Name):
                    fns = g.resolve_bare(owner, f.rel, expr.id)
                    return (fns[0], offset) if fns else None
                return None
            if isinstance(expr, ast.Name):
                bound = None
                if owner is not None:
                    for names, value in g._fn_facts[id(owner)][0]:
                        if expr.id in names:
                            bound = value
                if bound is not None:
                    expr = bound
                    continue
                fns = g.resolve_bare(owner, f.rel, expr.id)
                return (fns[0], 0) if fns else None
            return None
        return None


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------

#: jnp constructors whose first argument (or shape=) is a shape
_SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange",
                "broadcast_to", "tile", "reshape"}
#: sources that materialize a host Python value from device data
_NP_SINKS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}


@register
class RecompileHazardRule(Rule):
    """The perf program's zero-recompile invariant — planes packing,
    the one-kernel split, GOSS compaction and the MXU histograms all
    assume *the same shapes every iteration* — dies silently when a
    host-dynamic value (``.item()``, ``int()`` of a traced array,
    ``np.asarray`` of a device array) flows into a shape position:
    every new value retraces and recompiles the jit. The taint runs
    through local assignments (in source order, so rebinding to a
    static value clears it), into nested defs that close over tainted
    names, and interprocedurally into helpers that receive a tainted
    argument; sinks are ``jnp.zeros``/``reshape``-family shapes,
    ``grid=``, ``BlockSpec``/``ShapeDtypeStruct`` shapes, and
    ``lax.dynamic_slice`` / ``pl.ds`` *sizes* (dynamic starts stay
    legal — that is what ``dynamic_slice`` is for)."""

    id = "recompile-hazard"
    description = ("host-dynamic value (.item()/int()/np.asarray of "
                   "device data) flows into a shape position "
                   "(jnp.zeros/reshape, grid=, BlockSpec, "
                   "dynamic_slice sizes) — retraces every iteration")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hot_files = hot_subset(project)
        if not hot_files:
            return
        g = graph_for(project, hot_files, "hot")
        self._seen_sites: Set[Tuple[str, int, int]] = set()
        self._seen_scans: Set[Tuple[int, Tuple[str, ...]]] = set()
        work: List[Tuple[FuncInfo, Dict[str, str]]] = \
            [(fn, {}) for fn in g.funcs if fn.parent is None]
        while work:
            fn, taint = work.pop()
            key = (id(fn), tuple(sorted(taint)))
            if key in self._seen_scans:
                continue
            self._seen_scans.add(key)
            yield from self._scan_fn(g, fn, taint, work)

    # ------------------------------------------------------------- taint scan
    def _scan_fn(self, g: ProjectGraph, fn: FuncInfo,
                 taint: Dict[str, str],
                 work: List[Tuple[FuncInfo, Dict[str, str]]]
                 ) -> Iterator[Finding]:
        aliases = g.aliases[fn.file.rel]
        taint = dict(taint)
        stmts = [n for n in own_walk_cached(fn.node)
                 if isinstance(n, (ast.Assign, ast.AnnAssign, ast.Call))]
        stmts.sort(key=lambda n: (n.lineno, n.col_offset))
        for node in stmts:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                names = [t.id for t in targets if isinstance(t, ast.Name)]
                if not names or node.value is None:
                    continue
                src = self._dyn_source(node.value, aliases) \
                    or self._tainted_name(node.value, taint)
                for n in names:
                    if src is not None:
                        taint[n] = src
                    else:
                        taint.pop(n, None)
            else:
                yield from self._check_sinks(fn, node, taint, aliases)
                self._propagate_call(g, fn, node, taint, work)
        # nested defs close over the enclosing taint (minus shadowed params)
        for group in fn.children.values():
            for child in group:
                a = child.node.args
                shadow = {p.arg for p in a.posonlyargs + a.args
                          + a.kwonlyargs}
                inherited = {k: v for k, v in taint.items()
                             if k not in shadow}
                work.append((child, inherited))

    @staticmethod
    def _tainted_name(expr: ast.AST, taint: Dict[str, str]
                      ) -> Optional[str]:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in taint:
                return taint[n.id]
        return None

    @staticmethod
    def _dyn_source(expr: ast.AST, aliases: Dict[str, str]
                    ) -> Optional[str]:
        arrayish = HostSyncRule._arg_is_arrayish
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("item", "tolist") \
                    and not n.args and not n.keywords:
                return ".%s()" % n.func.attr
            cname = canonical_call(n, aliases)
            if cname in ("int", "float", "len") and n.args \
                    and arrayish(n.args[0], aliases):
                return "%s() of a traced value" % cname
            if cname in _NP_SINKS and n.args \
                    and arrayish(n.args[0], aliases):
                return "%s() of a device array" % dotted(n.func)
            if cname == "jax.device_get":
                return "jax.device_get()"
        return None

    # ---------------------------------------------------------------- sinks
    def _check_sinks(self, fn: FuncInfo, node: ast.Call,
                     taint: Dict[str, str],
                     aliases: Dict[str, str]) -> Iterator[Finding]:
        if not taint:
            return
        cname = canonical_call(node, aliases)
        tail = cname.rsplit(".", 1)[-1]
        shape_args: List[ast.AST] = []
        sink = None
        if cname.startswith("jax.numpy.") and tail in _SHAPE_CTORS:
            if tail in ("reshape", "arange"):
                shape_args = list(node.args)
            elif node.args:
                shape_args = [node.args[0]]
            shape_args += [k.value for k in node.keywords
                           if k.arg == "shape"]
            sink = "%s(...) shape" % dotted(node.func)
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "reshape":
            shape_args = list(node.args)
            sink = ".reshape(...) shape"
        elif tail == "BlockSpec":
            shape_args = list(node.args) \
                + [k.value for k in node.keywords
                   if k.arg == "block_shape"]
            sink = "BlockSpec block shape"
        elif tail == "ShapeDtypeStruct":
            shape_args = node.args[:1] \
                + [k.value for k in node.keywords if k.arg == "shape"]
            sink = "ShapeDtypeStruct shape"
        elif cname.endswith(".dynamic_slice"):
            shape_args = node.args[2:3]
            sink = "dynamic_slice sizes"
        elif cname.endswith(".dynamic_slice_in_dim"):
            shape_args = node.args[2:3] \
                + [k.value for k in node.keywords
                   if k.arg == "slice_size"]
            sink = "dynamic_slice_in_dim slice_size"
        elif tail == "ds" and len(node.args) >= 2:
            shape_args = [node.args[1]]
            sink = "pl.ds window size"
        grid_kws = [k.value for k in node.keywords if k.arg == "grid"]
        for val, label in [(a, sink) for a in shape_args] \
                + [(kwv, "grid=") for kwv in grid_kws]:
            src = self._tainted_name(val, taint)
            if src is None:
                continue
            site = (fn.file.rel, node.lineno, node.col_offset)
            if site in self._seen_sites:
                return
            self._seen_sites.add(site)
            yield fn.file.finding(
                node, self.id,
                "host-dynamic value (%s) flows into %s in '%s' — the "
                "shape changes between iterations and every change "
                "retraces + recompiles the jit" % (src, label, fn.qual))
            return

    # ---------------------------------------------------- interprocedural
    @staticmethod
    def _propagate_call(g: ProjectGraph, fn: FuncInfo, node: ast.Call,
                        taint: Dict[str, str],
                        work: List[Tuple[FuncInfo, Dict[str, str]]]
                        ) -> None:
        if not taint or not isinstance(node.func, ast.Name):
            return
        for callee in g.resolve_bare(fn, fn.file.rel, node.func.id):
            if callee.node.args.vararg is not None:
                continue
            params = [a.arg for a in callee.node.args.posonlyargs
                      + callee.node.args.args]
            sub: Dict[str, str] = {}
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Starred):
                    sub = {}
                    break
                src = RecompileHazardRule._tainted_name(a, taint)
                if src is not None and i < len(params):
                    sub[params[i]] = src
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                src = RecompileHazardRule._tainted_name(kw.value, taint)
                if src is not None:
                    sub[kw.arg] = src
            if sub:
                work.append((callee, sub))
            break
