"""metric-name: Prometheus family hygiene for the telemetry registry.

``obs.prometheus_text`` renders every registry key through
``_prom_name`` (sanitize + ``lgbtpu_`` prefix) and a per-kind suffix
convention (counters ``_total``, timers ``_seconds_total`` +
``_calls_total``, gauges bare, histograms ``_bucket``/``_sum``/
``_count`` under the bare family). Two source-level mistakes survive
that rendering and corrupt the exposition downstream:

- a raw name with characters outside the blessed set (letters, digits,
  ``_:`` plus the ``/`` and ``.`` separators) sanitizes to ``_`` — two
  DIFFERENT source names can silently merge into one family, and the
  emitted family no longer reflects the source name;
- one family registered under two different types (e.g. the same name
  fed to both ``gauge`` and ``observe``): the exporter's first-family-
  wins dedupe drops one silently, and strict parsers reject a family
  with two ``# TYPE`` lines.

This rule resolves every *literal* registration site project-wide to
its emitted family name(s) and flags both. Dynamic names
(``"span_ms/" + name``) cannot be checked statically and are skipped.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Tuple

from ..core import Finding, Project, Rule, SourceFile, register

#: Telemetry methods that create an exposition family, mapped to the
#: (suffix, prometheus type) pairs obs.prometheus_text emits for them
_METHOD_FAMILIES = {
    "count": (("_total", "counter"),),
    "gauge": (("", "gauge"),),
    "add_time": (("_seconds_total", "counter"), ("_calls_total", "counter")),
    "timed": (("_seconds_total", "counter"), ("_calls_total", "counter")),
    "observe": (("", "histogram"),),
    "timed_observe": (("", "histogram"),),
}

#: the exposition-legal family shape (Prometheus data model)
_FAMILY_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")

#: characters a raw registry key may use: family-legal chars plus the
#: repo's two separator conventions ("/" and "."), which _prom_name
#: maps to "_" deterministically
_RAW_OK_RE = re.compile(r"[a-zA-Z0-9_:./]+\Z")


def _prom(name: str) -> str:
    # mirror of obs._prom_name — the linter must predict the exact
    # family the exporter will emit
    return "lgbtpu_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _registrations(f: SourceFile) -> Iterator[Tuple[ast.Call, str, str]]:
    """(call node, raw name, method) for every literal-name telemetry
    registration in ``f``. Receiver must BE (or end in) ``telemetry`` so
    ``itertools.count(...)`` / local histogram objects don't match."""
    for node in f.walk_nodes():
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        method = node.func.attr
        if method not in _METHOD_FAMILIES:
            continue
        recv = node.func.value
        recv_name = recv.id if isinstance(recv, ast.Name) \
            else recv.attr if isinstance(recv, ast.Attribute) else None
        if recv_name != "telemetry":
            continue
        if not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue   # dynamic name: not statically checkable
        yield node, first.value, method


@register
class MetricNameRule(Rule):
    """Telemetry registrations must yield exposition-legal Prometheus
    family names, and one family must not be registered under two
    different types (first-family-wins would silently drop one)."""

    id = "metric-name"
    description = ("telemetry metric name sanitizes ambiguously, or one "
                   "Prometheus family is registered under two types")

    def check_project(self, project: Project) -> Iterator[Finding]:
        # family -> (type, "file:line" of first registration)
        seen: Dict[str, Tuple[str, str]] = {}
        sites: List[Tuple[SourceFile, ast.Call, str, str]] = []
        for f in project.files:
            for node, raw, method in _registrations(f):
                sites.append((f, node, raw, method))
        # deterministic order: findings independent of file walk order
        sites.sort(key=lambda s: (s[0].rel, s[1].lineno, s[1].col_offset))
        for f, node, raw, method in sites:
            if not raw or not _RAW_OK_RE.match(raw):
                yield f.finding(
                    node, self.id,
                    "metric name %r sanitizes ambiguously; use only "
                    "[a-zA-Z0-9_:] with / or . as separators" % raw)
                continue
            for suffix, ptype in _METHOD_FAMILIES[method]:
                family = _prom(raw) + suffix
                if not _FAMILY_RE.match(family):
                    yield f.finding(
                        node, self.id,
                        "family %r is not a legal Prometheus metric "
                        "name" % family)
                    continue
                prev = seen.get(family)
                if prev is None:
                    seen[family] = (ptype, "%s:%d" % (f.rel, node.lineno))
                elif prev[0] != ptype:
                    yield f.finding(
                        node, self.id,
                        "family %r registered as %s here but as %s at "
                        "%s; one family, one type"
                        % (family, ptype, prev[0], prev[1]))
