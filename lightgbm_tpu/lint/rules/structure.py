"""Structural hygiene rules: unnamed-pallas-call, mutable-default,
module-mutable-state."""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Tuple

from ..astutil import dotted, is_mutable_literal, kwarg_names
from ..core import Finding, Rule, SourceFile, register

_MUTATOR_METHODS = {"append", "add", "update", "setdefault", "pop",
                    "popitem", "clear", "extend", "insert", "remove",
                    "discard"}


@register
class UnnamedPallasCallRule(Rule):
    """``pallas_call`` without ``name=`` drops the kernel's identity from
    profiler timelines and HLO dumps — PR 3's phase tracing (and every
    trace-driven bisect script) keys on those names."""

    id = "unnamed-pallas-call"
    description = "pallas_call without a name= (breaks phase tracing)"

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        for node in f.walk_nodes():
            if isinstance(node, ast.Call) \
                    and dotted(node.func).rsplit(".", 1)[-1] == "pallas_call" \
                    and "name" not in kwarg_names(node):
                yield f.finding(node, self.id,
                                "pallas_call without name= (kernel is "
                                "anonymous in traces and HLO dumps)")


@register
class MutableDefaultRule(Rule):
    """Mutable default arguments are shared across calls — with cached
    jitted callables (``_BLOCK_CACHE``) a leaked default outlives the
    Booster that wrote it."""

    id = "mutable-default"
    description = "mutable default argument (list/dict/set literal)"

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        for node in f.walk_nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                args = node.args
                for d in list(args.defaults) + [
                        d for d in args.kw_defaults if d is not None]:
                    if is_mutable_literal(d):
                        yield f.finding(
                            d, self.id,
                            "mutable default argument in '%s'"
                            % getattr(node, "name", "<lambda>"))


@register
class ModuleMutableStateRule(Rule):
    """Module-level mutable state written from function scope is a hidden
    process-global — telemetry belongs in the ``obs`` registry (locked,
    snapshot-able, reset-able), not in ad-hoc module dicts. Deliberate
    caches carry an inline disable naming their invariant."""

    id = "module-mutable-state"
    description = ("module-level mutable literal written from function "
                   "scope outside the obs registry")

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        if f.rel == "lightgbm_tpu/obs.py":
            return
        decls: Dict[str, ast.stmt] = {}
        for node in f.tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target = node.targets[0].id
                value = node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target = node.target.id
                value = node.value
            if target and is_mutable_literal(value):
                decls[target] = node
        if not decls:
            return
        writes: Dict[str, Tuple[int, str]] = {}

        def visit_fn(fn_node):
            for node in ast.walk(fn_node):
                name, how = None, ""
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in decls:
                            name, how = t.value.id, "subscript write"
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in decls \
                        and node.func.attr in _MUTATOR_METHODS:
                    name, how = node.func.value.id, \
                        ".%s()" % node.func.attr
                elif isinstance(node, ast.Global):
                    for n in node.names:
                        if n in decls:
                            name, how = n, "global rebind"
                if name and name not in writes:
                    writes[name] = (node.lineno, how)

        for node in f.walk_nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_fn(node)
        for name, decl in decls.items():
            if name in writes:
                line, how = writes[name]
                yield f.finding(
                    decl, self.id,
                    "module-level mutable '%s' written from function scope "
                    "(%s at line %d); use the obs registry or justify with "
                    "an inline disable" % (name, how, line))
