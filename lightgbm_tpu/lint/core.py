"""graftlint framework: findings, rule registry, suppression, baseline.

Pure stdlib + ``ast`` — importing this module must never import jax (the
full-repo lint runs in tier-1 on CPU and stays well under the ~5 s budget;
parsing is the only cost).

Suppression syntax (same line as the finding)::

    t0 = time.time()   # graftlint: disable=naked-timer
    cache = {}         # graftlint: disable=module-mutable-state -- why...
    x = foo()          # graftlint: disable   (suppresses every rule)

Baseline: ``lint_baseline.json`` at the repo root freezes pre-existing
findings. Entries key on ``(path, rule, stripped source line)`` with a
count, NOT on line numbers, so unrelated edits that shift lines do not
unfreeze old findings. ``scripts/lint.py --update-baseline`` rewrites it.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

BASELINE_NAME = "lint_baseline.json"

#: repo-relative roots linted by default (ISSUE 4 scope: the package, the
#: perf-harness scripts, and the bench driver; tests are free to use raw
#: timers and host syncs).
DEFAULT_PATHS = ("lightgbm_tpu", "scripts", "bench.py")

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=([A-Za-z0-9_,\- ]+))?")


@dataclass(frozen=True)
class Finding:
    """One lint hit. ``text`` (the stripped source line) is the baseline
    key component, so findings survive line renumbering."""

    path: str          # repo-relative, posix separators
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    rule: str          # rule id, e.g. "naked-timer"
    message: str
    text: str = ""

    def render(self) -> str:
        return "%s:%d:%d: %s %s" % (self.path, self.line, self.col,
                                    self.rule, self.message)

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.text)


class SourceFile:
    """One parsed file handed to rules. Parse errors surface as a
    ``syntax-error`` finding instead of crashing the whole lint."""

    def __init__(self, abspath: str, rel: str) -> None:
        self.abspath = abspath
        self.rel = rel.replace(os.sep, "/")
        with open(abspath, "r", encoding="utf-8", errors="replace") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.source, filename=self.rel)
        except SyntaxError as e:  # pragma: no cover - repo parses today
            self.parse_error = e

    def walk_nodes(self) -> list:
        """Every AST node of this file, cached: five per-file rules scan
        the full tree, and one materialized list beats five generator
        walks inside the <5s full-lint budget."""
        nodes = self.__dict__.get("_walk_nodes")
        if nodes is None:
            nodes = self._walk_nodes = \
                list(ast.walk(self.tree)) if self.tree is not None else []
        return nodes

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, node_or_line, rule: str, message: str,
                col: Optional[int] = None) -> Finding:
        if isinstance(node_or_line, int):
            line, c = node_or_line, col or 0
        else:
            line = getattr(node_or_line, "lineno", 1)
            c = getattr(node_or_line, "col_offset", 0) if col is None else col
        return Finding(self.rel, line, c, rule, message, self.line_text(line))

    def disabled_rules(self, lineno: int) -> Optional[set]:
        """Rules suppressed on ``lineno``; empty set means suppress ALL."""
        m = _DISABLE_RE.search(self.lines[lineno - 1]) \
            if 1 <= lineno <= len(self.lines) else None
        if m is None:
            return None
        if m.group(1) is None:
            return set()
        return {r.strip() for r in m.group(1).replace(" ", ",").split(",")
                if r.strip()}


@dataclass
class Project:
    """All files of one lint run, for rules that need cross-file context
    (the host-sync rule builds a call graph over the hot modules)."""

    root: str
    files: List[SourceFile] = field(default_factory=list)

    def by_rel(self, rel: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.rel == rel:
                return f
        return None


class Rule:
    """Base rule. Subclasses set ``id``/``description`` and implement
    either :meth:`check_file` (per-file) or :meth:`check_project`
    (cross-file). Registration is by :func:`register` decorator."""

    id: str = ""
    description: str = ""

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        return iter(())

    def check_project(self, project: Project) -> Iterator[Finding]:
        return iter(())


_REGISTRY: Dict[str, Rule] = {}  # graftlint: disable=module-mutable-state -- the rule registry is the linter's own plugin seam


def register(cls):
    """Class decorator adding a rule (by instance) to the registry."""
    inst = cls()
    if not inst.id:
        raise ValueError("rule %s has no id" % cls.__name__)
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Running
# ---------------------------------------------------------------------------

def _iter_py_files(root: str, paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            if ap.endswith(".py"):
                yield ap
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith((".", "__pycache__")))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


@dataclass
class LintResult:
    findings: List[Finding]          # after inline suppression
    suppressed: List[Finding]        # killed by # graftlint: disable
    project: Project

    def render(self) -> str:
        return "\n".join(f.render() for f in self.findings)


def run(root: str, paths: Sequence[str] = DEFAULT_PATHS,
        rules: Optional[Iterable[str]] = None) -> LintResult:
    """Lint ``paths`` (relative to ``root``) with the registered rules.

    Returns every finding that survives inline suppression; baseline
    filtering is a separate step (:func:`split_new_findings`) so callers
    can render both views.
    """
    root = os.path.abspath(root)
    project = Project(root=root)
    for ap in _iter_py_files(root, paths):
        rel = os.path.relpath(ap, root)
        project.files.append(SourceFile(ap, rel))

    active = all_rules()
    if rules is not None:
        wanted = set(rules)
        unknown = wanted - set(active)
        if unknown:
            raise ValueError("unknown rule(s): %s" % ", ".join(sorted(unknown)))
        active = {k: v for k, v in active.items() if k in wanted}

    raw: List[Finding] = []
    for f in project.files:
        if f.parse_error is not None:  # pragma: no cover - repo parses today
            raw.append(f.finding(f.parse_error.lineno or 1, "syntax-error",
                                 str(f.parse_error)))
            continue
        for rule in active.values():
            raw.extend(rule.check_file(f))
    for rule in active.values():
        raw.extend(rule.check_project(project))

    kept, suppressed = [], []
    for fi in raw:
        sf = project.by_rel(fi.path)
        dis = sf.disabled_rules(fi.line) if sf is not None else None
        if dis is not None and (not dis or fi.rule in dis):
            suppressed.append(fi)
        else:
            kept.append(fi)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings=kept, suppressed=suppressed, project=project)


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def baseline_from_findings(findings: Sequence[Finding]) -> dict:
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.baseline_key()] = counts.get(f.baseline_key(), 0) + 1
    entries = [{"path": p, "rule": r, "text": t, "count": c}
               for (p, r, t), c in sorted(counts.items())]
    return {"version": 1, "findings": entries}


def save_baseline(path: str, baseline: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> dict:
    if not os.path.exists(path):
        return {"version": 1, "findings": []}
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def stale_baseline_entries(root: str, baseline: dict) -> List[dict]:
    """Baseline entries whose ``(path, text)`` no longer matches any
    source line — the frozen finding was fixed (or its file deleted)
    without the baseline shrinking. Text-based, like the baseline keys
    themselves, so the check needs no lint run: ``check.sh`` fails on
    drift in every mode, including ``--fast`` where only changed files
    are linted."""
    out: List[dict] = []
    cache: Dict[str, set] = {}
    for e in baseline.get("findings", []):
        path = e.get("path", "")
        lines = cache.get(path)
        if lines is None:
            try:
                with open(os.path.join(root, path), "r", encoding="utf-8",
                          errors="replace") as fh:
                    lines = {ln.strip() for ln in fh}
            except OSError:
                lines = set()
            cache[path] = lines
        if e.get("text", "") not in lines:
            out.append(e)
    return out


def split_new_findings(findings: Sequence[Finding], baseline: dict
                       ) -> Tuple[List[Finding], List[Finding]]:
    """Partition into (new, baselined). A finding is baselined while its
    ``(path, rule, text)`` entry has remaining count budget."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in baseline.get("findings", []):
        key = (e["path"], e["rule"], e["text"])
        budget[key] = budget.get(key, 0) + int(e.get("count", 1))
    new, old = [], []
    for f in findings:
        k = f.baseline_key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
