"""graftlint: JAX-invariant static analysis for the LightGBM-TPU codebase.

The trainer's wall-clock rests on invariants no type checker knows about:
trusted timers only (PERF.md measurement discipline), no host-device syncs
inside traced hot phases, explicit dtypes in the ops kernels, named
``pallas_call``s (phase tracing), and no hidden mutable state. graftlint
makes them checkable in tier-1, on CPU, with no TPU and no jax import.

Layers:

- :mod:`.core` — the framework: :class:`Finding`, :class:`Rule`, the rule
  registry, ``# graftlint: disable=<rule>`` inline suppression, and the
  committed ``lint_baseline.json`` (pre-existing findings are frozen; new
  ones fail).
- :mod:`.rules` — the rule set targeting this repo's real hazard classes.

Entry points: ``scripts/lint.py`` (CLI) and :func:`run` (library/tests).
"""
from .core import (  # noqa: F401
    BASELINE_NAME,
    DEFAULT_PATHS,
    Finding,
    LintResult,
    Project,
    Rule,
    all_rules,
    baseline_from_findings,
    load_baseline,
    register,
    run,
    save_baseline,
    split_new_findings,
    stale_baseline_entries,
)
from . import rules  # noqa: F401  (importing registers the rule set)
