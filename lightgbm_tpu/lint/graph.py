"""Interprocedural dataflow engine for graftlint (ISSUE 6 tentpole).

PR 4's ``HostSyncRule`` carried a private lexically-scoped call-graph
builder; the hazards that matter after PR 5 (a daemon batcher thread,
stdlib-HTTP handler threads, version-keyed caches shared between a
training thread and serving threads) are interprocedural and span
packages, so the resolver now lives here as a reusable engine:

- a project-wide **symbol table**: every function/method with its lexical
  position (enclosing function, enclosing class, file top-level), every
  class with its bases and methods;
- a small **type lattice** (abstract values are sets of project class
  quals plus ``ext:<module.Name>`` markers for external constructors)
  propagated to fixpoint through local assignments, ``self.attr =``
  writes, call-site parameter binding and return values — enough to
  resolve ``self._session.dispatch(...)`` to ``PredictSession.dispatch``
  instead of every method named ``dispatch``;
- a **call graph** over bare-name calls (innermost lexical scope first,
  never methods), attribute calls (typed receiver first, falling back to
  by-name method matching, suppressed for known-external receivers) and
  function-valued arguments (``lax.while_loop``/``scan``/``vmap`` bodies,
  ``partial``-wrapped jit entries);
- **entry discovery**: jit entries (decorators plus functions handed by
  value to ``jax.jit``/``partial``) and thread entries
  (``threading.Thread(target=...)``/``Timer``, ``concurrent.futures``
  ``submit``, and ``do_*`` methods of ``BaseHTTPRequestHandler``
  subclasses);
- **reachability** closures over the above.

Pure stdlib + ``ast``; importing this module must never import jax. Built
once per (lint run, file subset) and cached on the :class:`~.core.Project`
(see :func:`graph_for`).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from .astutil import (call_name_args, canonical_call, dotted,
                      import_aliases_cached, own_walk_cached)

#: jit / partial wrapper heads (entries by value)
JIT_HEADS = {"jax.jit", "jit"}
PARTIAL_HEADS = {"partial", "functools.partial", "_partial"}

#: constructors whose result is a freshly built, not-yet-shared object
#: (writes through such locals are construction, not mutation)
_THREAD_CTORS = {"threading.Thread", "threading.Timer"}

EXT = "ext:"  # type-tag prefix for external (non-project) constructor types


def _fresh_ctor_name(name: str) -> bool:
    """Factory receivers whose call result is a fresh instance."""
    return name == "cls" or name.endswith("_cls")

#: bare-name constructors of builtin/stdlib containers and scalars: typing
#: their results ``ext:`` suppresses the by-name method fallback, so
#: ``self._warm.add(x)`` on a set never resolves to a project ``add``
_BUILTIN_CTORS = {"set", "dict", "list", "tuple", "frozenset", "bytearray",
                  "bytes", "str", "int", "float", "bool", "object",
                  "complex"}


class FuncInfo:
    """One function/method with its lexical position in the project."""

    __slots__ = ("node", "file", "qual", "name", "parent", "cls",
                 "children", "edges", "confined_edges", "is_method")

    def __init__(self, node, file, qual: str, parent: Optional["FuncInfo"],
                 cls: Optional["ClassInfo"]) -> None:
        self.node = node
        self.file = file
        self.qual = qual
        self.name = node.name
        self.parent = parent
        self.cls = cls
        self.is_method = cls is not None
        self.children: Dict[str, List["FuncInfo"]] = {}
        self.edges: List["FuncInfo"] = []
        #: method calls whose receiver is a freshly-constructed local
        #: (``b = Booster(...); b.refit(...)``): the object is confined
        #: to the constructing frame, so thread-reachability closures may
        #: stop at these edges (the subtree runs on the thread but only
        #: touches thread-local instance state). Full closures (jit
        #: tracing, host-sync) still follow them.
        self.confined_edges: List["FuncInfo"] = []

    @property
    def self_name(self) -> Optional[str]:
        """The receiver parameter name ('self') for instance methods."""
        if not self.is_method:
            return None
        if any(dotted(d) == "staticmethod" for d in self.node.decorator_list):
            return None
        args = self.node.args.posonlyargs + self.node.args.args
        return args[0].arg if args else None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<fn %s:%s>" % (self.file.rel, self.qual)


class ClassInfo:
    """One class with its bases (dotted names) and directly-defined
    methods."""

    __slots__ = ("node", "file", "qual", "name", "bases", "methods", "parent")

    def __init__(self, node, file, qual: str,
                 parent: Optional[FuncInfo]) -> None:
        self.node = node
        self.file = file
        self.qual = qual
        self.name = node.name
        self.bases = [dotted(b) for b in node.bases]
        self.methods: Dict[str, FuncInfo] = {}
        self.parent = parent

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<class %s:%s>" % (self.file.rel, self.qual)


def is_jit_decorator(dec: ast.AST) -> bool:
    name = dotted(dec)
    if name in JIT_HEADS or name.endswith(".jit"):
        return True
    if isinstance(dec, ast.Call):
        fname = dotted(dec.func)
        if fname in JIT_HEADS or fname.endswith(".jit"):
            return True
        if fname in PARTIAL_HEADS or fname.endswith(".partial"):
            return any(dotted(a) in JIT_HEADS or dotted(a).endswith(".jit")
                       for a in dec.args)
    return False


class ProjectGraph:
    """Symbol table + types + call graph over one file subset."""

    def __init__(self, files: Sequence) -> None:
        self.files = [f for f in files if f.tree is not None]
        self.funcs: List[FuncInfo] = []
        self.classes: List[ClassInfo] = []
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self.methods_by_name: Dict[str, List[FuncInfo]] = {}
        self.top_level: Dict[str, Dict[str, List[FuncInfo]]] = {}
        self.aliases: Dict[str, Dict[str, str]] = {}
        # dataflow facts (fixpoint-iterated)
        self.attr_types: Dict[Tuple[str, str], Set[str]] = {}
        self.param_types: Dict[Tuple[int, str], Set[str]] = {}
        self.return_types: Dict[int, Set[str]] = {}
        self.global_types: Dict[Tuple[str, str], Set[str]] = {}
        #: attr name -> class quals that assign ``self.<attr> =`` anywhere
        self.attr_owners: Dict[str, Set[str]] = {}
        self._value_entries: List[FuncInfo] = []
        self._fresh_cache: Dict[int, Set[str]] = {}
        self._global_funcs: Optional[Dict[str, List[FuncInfo]]] = None
        self._collect()
        self._extract_facts()
        self._infer_types()
        self._build_edges()

    # ----------------------------------------------------------- collection
    def _collect(self) -> None:
        for f in self.files:
            self.aliases[f.rel] = import_aliases_cached(f)
            self.top_level.setdefault(f.rel, {})
            self._walk_block(f, f.tree, "", None, None)

    def _walk_block(self, f, parent, prefix: str, encl: Optional[FuncInfo],
                    cls: Optional[ClassInfo]) -> None:
        for node in ast.iter_child_nodes(parent):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = FuncInfo(node, f, prefix + node.name, encl, cls)
                self.funcs.append(info)
                if cls is not None:
                    cls.methods.setdefault(node.name, info)
                    self.methods_by_name.setdefault(node.name, []).append(info)
                elif encl is None:
                    self.top_level[f.rel].setdefault(node.name, []).append(info)
                else:
                    encl.children.setdefault(node.name, []).append(info)
                self._walk_block(f, node, info.qual + ".", info, None)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(node, f, prefix + node.name, encl)
                self.classes.append(ci)
                self.classes_by_name.setdefault(node.name, []).append(ci)
                self._walk_block(f, node, ci.qual + ".", encl, ci)
            else:
                self._walk_block(f, node, prefix, encl, cls)

    # ----------------------------------------------------------- resolution
    def resolve_bare(self, ctx: Optional[FuncInfo], rel: str,
                     name: str) -> List[FuncInfo]:
        """Bare-name call resolution: innermost lexical scope outward, then
        file top-level, then project top-level. Never resolves to methods
        (the FusedTrainer.flush false-positive class, PR 4)."""
        cur = ctx
        while cur is not None:
            if name in cur.children:
                return cur.children[name]
            cur = cur.parent
        if name in self.top_level.get(rel, {}):
            return self.top_level[rel][name]
        gf = self._global_funcs
        if gf is None:
            gf = self._global_funcs = {}
            for tl in self.top_level.values():
                for n, fns in tl.items():
                    gf.setdefault(n, []).extend(fns)
        return gf.get(name, [])

    def resolve_class(self, rel: str, name: str) -> List[ClassInfo]:
        """A (possibly dotted/aliased) name to project classes, matching on
        the final segment."""
        tail = self.aliases.get(rel, {}).get(name, name).rsplit(".", 1)[-1]
        return self.classes_by_name.get(tail, [])

    def class_method(self, ci: ClassInfo, name: str,
                     _depth: int = 0) -> Optional[FuncInfo]:
        """Method lookup through project-local bases (bounded depth)."""
        if name in ci.methods:
            return ci.methods[name]
        if _depth >= 4:
            return None
        for b in ci.bases:
            for bc in self.classes_by_name.get(b.rsplit(".", 1)[-1], []):
                m = self.class_method(bc, name, _depth + 1)
                if m is not None:
                    return m
        return None

    def _class_by_qual(self, qual: str) -> Optional[ClassInfo]:
        for ci in self.classes_by_name.get(qual.rsplit(".", 1)[-1], []):
            if ci.qual == qual:
                return ci
        return None

    # ------------------------------------------------------- type inference
    def expr_type(self, owner: Optional[FuncInfo], f,
                  env: Dict[str, Set[str]], node: ast.AST) -> Set[str]:
        """Abstract type of an expression: project class quals and/or
        ``ext:`` markers; empty set means unknown."""
        if isinstance(node, ast.Constant):
            return {EXT + "builtins." + type(node.value).__name__}
        if isinstance(node, (ast.Dict, ast.DictComp)):
            return {EXT + "builtins.dict"}
        if isinstance(node, (ast.List, ast.ListComp)):
            return {EXT + "builtins.list"}
        if isinstance(node, (ast.Set, ast.SetComp)):
            return {EXT + "builtins.set"}
        if isinstance(node, (ast.Tuple, ast.GeneratorExp)):
            return {EXT + "builtins.tuple"}
        if isinstance(node, ast.JoinedStr):
            return {EXT + "builtins.str"}
        if isinstance(node, ast.Name):
            if owner is not None and node.id == owner.self_name \
                    and owner.cls is not None:
                return {owner.cls.qual}
            if node.id in env:
                return env[node.id]
            if owner is not None and (id(owner), node.id) in self.param_types:
                return self.param_types[(id(owner), node.id)]
            got = self.global_types.get((f.rel, node.id))
            if got:
                return got
            # an imported module-level singleton (unique tail match)
            target = self.aliases.get(f.rel, {}).get(node.id)
            if target:
                tail = target.rsplit(".", 1)[-1]
                hits = [t for (rel, n), t in self.global_types.items()
                        if n == tail]
                if len(hits) == 1:
                    return hits[0]
            return set()
        if isinstance(node, ast.Attribute):
            out: Set[str] = set()
            for t in self.expr_type(owner, f, env, node.value):
                if t.startswith(EXT):
                    continue
                out |= self.attr_types.get((t, node.attr), set())
            return out
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name:
                head = name.split(".")[0]
                classes = self.resolve_class(f.rel, name) if "." not in name \
                    else self.resolve_class(f.rel, name.rsplit(".", 1)[-1])
                if "." not in name and classes:
                    return {c.qual for c in classes}
                canon = canonical_call(node, self.aliases.get(f.rel, {}))
                if "." in name and head in self.aliases.get(f.rel, {}):
                    # module.Attr(...) through an import: external unless the
                    # tail names a project class
                    if classes and any(c.name == name.rsplit(".", 1)[-1]
                                       for c in classes):
                        return {c.qual for c in classes}
                    return {EXT + canon}
                if "." not in name:
                    fns = self.resolve_bare(owner, f.rel, name)
                    out = set()
                    for fn in fns:
                        out |= self.return_types.get(id(fn), set())
                    if out:
                        return out
                    if not fns:
                        if name in _BUILTIN_CTORS:
                            return {EXT + "builtins." + name}
                        # imported external constructor used bare
                        # (``deque(...)``, ``Future()``)
                        target = self.aliases.get(f.rel, {}).get(name)
                        if target and (name[:1].isupper()
                                       or target.startswith("collections.")):
                            return {EXT + target}
            # method call: type through resolved targets' returns
            if isinstance(node.func, ast.Attribute):
                out = set()
                for m in self._typed_methods(owner, f, env, node.func):
                    out |= self.return_types.get(id(m), set())
                return out
            return set()
        return set()

    def _typed_methods(self, owner, f, env,
                       attr: ast.Attribute) -> List[FuncInfo]:
        """Resolve ``<recv>.name`` to methods via the receiver's abstract
        type; empty when the receiver is known-external."""
        rtypes = self.expr_type(owner, f, env, attr.value)
        targets: List[FuncInfo] = []
        ext_only = bool(rtypes) and all(t.startswith(EXT) for t in rtypes)
        for t in rtypes:
            if t.startswith(EXT):
                continue
            ci = self._class_by_qual(t)
            if ci is not None:
                m = self.class_method(ci, attr.attr)
                if m is not None:
                    targets.append(m)
        if targets:
            return targets
        if ext_only:
            return []
        # a class used as a namespace: Log.debug(...)
        if isinstance(attr.value, ast.Name):
            for ci in self.resolve_class(f.rel, attr.value.id):
                m = self.class_method(ci, attr.attr)
                if m is not None:
                    targets.append(m)
            if targets:
                return targets
        return self.methods_by_name.get(attr.attr, [])

    def _extract_facts(self) -> None:
        """One AST pass per scope, reused by every fixpoint round — the
        type iteration must not pay a fresh tree walk per function per
        round. Per function: local Name assignments (source order, for
        ``_local_env``), ``self.<attr> =`` sites, return values, calls."""
        self._mod_assigns: Dict[str, List[Tuple[str, ast.AST]]] = {}
        for f in self.files:
            pairs: List[Tuple[str, ast.AST]] = []
            for node in own_walk_cached(f.tree):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    pairs.append((node.targets[0].id, node.value))
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name) \
                        and node.value is not None:
                    pairs.append((node.target.id, node.value))
            self._mod_assigns[f.rel] = pairs
        self._fn_facts: Dict[int, tuple] = {}
        for fn in self.funcs:
            sname = fn.self_name
            locals_: List[Tuple[List[str], ast.AST]] = []
            attrs: List[Tuple[str, Optional[ast.AST]]] = []
            rets: List[ast.AST] = []
            calls: List[ast.Call] = []
            for node in own_walk_cached(fn.node):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = node.targets \
                        if isinstance(node, ast.Assign) else [node.target]
                    names = [t.id for t in targets
                             if isinstance(t, ast.Name)]
                    if names and node.value is not None:
                        locals_.append((names, node.value))
                    if fn.cls is not None:
                        for tgt in targets:
                            if isinstance(tgt, ast.Attribute) \
                                    and isinstance(tgt.value, ast.Name) \
                                    and tgt.value.id == sname:
                                attrs.append((tgt.attr, node.value))
                elif isinstance(node, ast.Return) \
                        and node.value is not None:
                    rets.append(node.value)
                elif isinstance(node, ast.Call):
                    calls.append(node)
            self._fn_facts[id(fn)] = (locals_, attrs, rets, calls)

    def _local_env(self, fn: FuncInfo) -> Dict[str, Set[str]]:
        env: Dict[str, Set[str]] = {}
        for names, value in self._fn_facts[id(fn)][0]:
            t = self.expr_type(fn, fn.file, env, value)
            if not t:
                continue
            for name in names:
                env.setdefault(name, set()).update(t)
        return env

    def _infer_types(self) -> None:
        # module-level constructor assignments seed global singleton types
        for _round in range(5):
            before = (sum(len(v) for v in self.attr_types.values()),
                      sum(len(v) for v in self.param_types.values()),
                      sum(len(v) for v in self.return_types.values()),
                      sum(len(v) for v in self.global_types.values()))
            for f in self.files:
                for name, value in self._mod_assigns[f.rel]:
                    t = self.expr_type(None, f, {}, value)
                    if t:
                        self.global_types.setdefault(
                            (f.rel, name), set()).update(t)
            for fn in self.funcs:
                env = self._local_env(fn)
                _, attrs, rets, calls = self._fn_facts[id(fn)]
                for attr, value in attrs:
                    self.attr_owners.setdefault(
                        attr, set()).add(fn.cls.qual)
                    t = self.expr_type(fn, fn.file, env, value) \
                        if value is not None else set()
                    if t:
                        self.attr_types.setdefault(
                            (fn.cls.qual, attr), set()).update(t)
                for value in rets:
                    t = self.expr_type(fn, fn.file, env, value)
                    if t:
                        self.return_types.setdefault(
                            id(fn), set()).update(t)
                for node in calls:
                    self._bind_params(fn, env, node)
            after = (sum(len(v) for v in self.attr_types.values()),
                     sum(len(v) for v in self.param_types.values()),
                     sum(len(v) for v in self.return_types.values()),
                     sum(len(v) for v in self.global_types.values()))
            if after == before:
                break

    def _bind_params(self, owner: Optional[FuncInfo],
                     env: Dict[str, Set[str]], node: ast.Call) -> None:
        """Flow argument types into the parameters of resolved callees."""
        f = owner.file if owner is not None else None
        if f is None:
            return
        callees: List[Tuple[FuncInfo, int]] = []  # (fn, positional offset)
        name = dotted(node.func)
        if name and "." not in name:
            for ci in self.resolve_class(f.rel, name):
                init = self.class_method(ci, "__init__")
                if init is not None:
                    callees.append((init, 1))
            if not callees:
                for fn2 in self.resolve_bare(owner, f.rel, name):
                    callees.append((fn2, 0))
        elif isinstance(node.func, ast.Attribute):
            for m in self._typed_methods(owner, f, env, node.func):
                callees.append((m, 1 if m.is_method else 0))
        for fn2, off in callees:
            params = [a.arg for a in fn2.node.args.posonlyargs
                      + fn2.node.args.args][off:]
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Starred) or i >= len(params):
                    break
                t = self.expr_type(owner, f, env, a)
                if t:
                    self.param_types.setdefault(
                        (id(fn2), params[i]), set()).update(t)
            kwnames = {a.arg for a in fn2.node.args.args
                       + fn2.node.args.kwonlyargs}
            for kw in node.keywords:
                if kw.arg in kwnames:
                    t = self.expr_type(owner, f, env, kw.value)
                    if t:
                        self.param_types.setdefault(
                            (id(fn2), kw.arg), set()).update(t)

    # ------------------------------------------------------------ call graph
    def fresh_locals(self, fn: FuncInfo) -> Set[str]:
        """Local names bound to a freshly-constructed, not-yet-shared
        object anywhere in ``fn``: direct project-class constructor
        calls, ``cls(...)``-style factory receivers and ``__new__``.
        Order-free (a name counts for the whole function body). Memoized
        off the extracted assignment facts — re-walking every function
        body here was a measurable slice of the <5s lint budget."""
        cached = self._fresh_cache.get(id(fn))
        if cached is not None:
            return cached
        fresh: Set[str] = set()
        for names, value in self._fn_facts[id(fn)][0]:
            if not isinstance(value, ast.Call):
                continue
            vname = value.func
            if isinstance(vname, ast.Name) \
                    and (self.resolve_class(fn.file.rel, vname.id)
                         or _fresh_ctor_name(vname.id)):
                fresh.update(names)
            elif isinstance(vname, ast.Attribute) \
                    and vname.attr == "__new__":
                fresh.update(names)
        self._fresh_cache[id(fn)] = fresh
        return fresh

    def _build_edges(self) -> None:
        envs = {id(fn): self._local_env(fn) for fn in self.funcs}
        for f in self.files:
            self._scan_calls(None, f, f.tree, {})
        for fn in self.funcs:
            self._scan_calls(fn, fn.file, fn.node, envs[id(fn)])

    def _scan_calls(self, owner: Optional[FuncInfo], f, body,
                    env: Dict[str, Set[str]]) -> None:
        aliases = self.aliases.get(f.rel, {})
        fresh = self.fresh_locals(owner) if owner is not None else set()
        # function bodies reuse the call list extracted for the type
        # fixpoint; only module level pays a fresh walk
        if owner is not None:
            calls = self._fn_facts[id(owner)][3]
        else:
            calls = [n for n in own_walk_cached(body)
                     if isinstance(n, ast.Call)]
        for node in calls:
            cname = canonical_call(node, aliases)
            wraps = (cname in JIT_HEADS or cname.endswith(".jit")
                     or cname in PARTIAL_HEADS)
            for a in call_name_args(node):
                for target in self.resolve_bare(owner, f.rel, a.id):
                    if wraps:
                        self._value_entries.append(target)
                    elif owner is not None:
                        owner.edges.append(target)
            if owner is None:
                continue
            fn = node.func
            if isinstance(fn, ast.Name):
                owner.edges.extend(self.resolve_bare(owner, f.rel, fn.id))
            elif isinstance(fn, ast.Attribute):
                targets = self._typed_methods(owner, f, env, fn)
                if isinstance(fn.value, ast.Name) and fn.value.id in fresh:
                    owner.confined_edges.extend(targets)
                else:
                    owner.edges.extend(targets)

    # --------------------------------------------------------------- entries
    def jit_entries(self) -> List[FuncInfo]:
        """Functions that start a jit trace: jit-decorated plus handed by
        value to ``jax.jit``/``partial``."""
        out = [fn for fn in self.funcs
               if any(is_jit_decorator(d) for d in fn.node.decorator_list)]
        out.extend(self._value_entries)
        return out

    def _resolve_callable_arg(self, owner: Optional[FuncInfo], f,
                              node: ast.AST) -> List[FuncInfo]:
        """A thread-target expression to functions: bare names lexically,
        ``self.m`` / ``obj.m`` through the receiver's class."""
        if isinstance(node, ast.Name):
            return self.resolve_bare(owner, f.rel, node.id)
        if isinstance(node, ast.Attribute):
            env = self._local_env(owner) if owner is not None else {}
            return self._typed_methods(owner, f, env, node)
        return []

    def thread_entries(self) -> List[Tuple[FuncInfo, str]]:
        """(function, root label) pairs for every discovered thread root:
        ``threading.Thread(target=...)`` / ``Timer``, functions submitted
        to ``concurrent.futures`` executors, and ``do_*`` methods of
        ``BaseHTTPRequestHandler`` subclasses."""
        out: List[Tuple[FuncInfo, str]] = []
        seen: Set[int] = set()

        def add(fns: Iterable[FuncInfo], label: str) -> None:
            for fn in fns:
                if id(fn) not in seen:
                    seen.add(id(fn))
                    out.append((fn, label))

        for f in self.files:
            aliases = self.aliases.get(f.rel, {})
            scopes: List[Tuple[Optional[FuncInfo], ast.AST]] = [(None, f.tree)]
            scopes += [(fn, fn.node) for fn in self.funcs if fn.file is f]
            for owner, body in scopes:
                for node in own_walk_cached(body):
                    if not isinstance(node, ast.Call):
                        continue
                    cname = canonical_call(node, aliases)
                    if cname in _THREAD_CTORS or cname.endswith(".Thread"):
                        target = None
                        for kw in node.keywords:
                            if kw.arg in ("target", "function"):
                                target = kw.value
                        if target is None and cname.endswith("Timer") \
                                and len(node.args) >= 2:
                            target = node.args[1]
                        elif target is None and len(node.args) >= 2:
                            target = node.args[1]  # Thread(group, target)
                        if target is not None:
                            add(self._resolve_callable_arg(owner, f, target),
                                "thread(%s:%d)" % (f.rel, node.lineno))
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "submit" and node.args:
                        env = self._local_env(owner) if owner else {}
                        rtypes = self.expr_type(owner, f, env,
                                                node.func.value)
                        if any(t.startswith(EXT) and "futures" in t
                               for t in rtypes):
                            add(self._resolve_callable_arg(
                                owner, f, node.args[0]),
                                "executor(%s:%d)" % (f.rel, node.lineno))
        for ci in self.classes:
            if any(b.rsplit(".", 1)[-1] in ("BaseHTTPRequestHandler",
                                            "SimpleHTTPRequestHandler",
                                            "CGIHTTPRequestHandler")
                   for b in ci.bases):
                add((m for name, m in sorted(ci.methods.items())
                     if name.startswith("do_")),
                    "http-handler(%s)" % ci.qual)
        return out

    # ---------------------------------------------------------- reachability
    def closure(self, entries: Iterable[FuncInfo],
                confined: bool = True) -> Set[int]:
        """ids of every function reachable from ``entries`` through call
        edges; nested defs of reachable functions are reachable (they
        trace/run with their parent). ``confined=False`` stops at
        fresh-receiver call edges (see :attr:`FuncInfo.confined_edges`):
        thread-reachability closures use it so a worker that builds and
        drives its own objects does not drag their whole class surface
        into the shared-state universe."""
        hot: Set[int] = set()
        work: List[FuncInfo] = []
        for e in entries:
            if id(e) not in hot:
                hot.add(id(e))
                work.append(e)
        while work:
            cur = work.pop()
            nxt: List[FuncInfo] = list(cur.edges)
            if confined:
                nxt.extend(cur.confined_edges)
            for group in cur.children.values():
                nxt.extend(group)
            for fn in nxt:
                if id(fn) not in hot:
                    hot.add(id(fn))
                    work.append(fn)
        return hot

    # ----------------------------------------------------------- ref dataflow
    def ref_events(self, fn: FuncInfo,
                   refs: Dict[str, str]) -> List["RefEvent"]:
        """Ordered read/write facts on kernel ``Ref`` parameters (ISSUE
        19 tentpole): the events of ``fn``'s full body — nested
        ``fori_loop`` bodies included — on the refs in ``refs`` (local
        name -> canonical name), with calls to project helpers that
        receive a tracked ref inlined at the call site (bounded depth,
        cycle-guarded). See :class:`RefEvent`."""
        return _ref_events_scan(self, fn, refs, 0, {id(fn)})


class RefEvent:
    """One ordered access to a Pallas kernel ``Ref`` parameter.

    ``kind`` is ``"read"`` or ``"write"``; ``ref`` is the *canonical*
    ref name handed to :meth:`ProjectGraph.ref_events` (stable across
    call inlining, so a helper that receives ``work_in`` under another
    parameter name still reports events against ``work_in``); ``label``
    is the region tag — the dotted/constant text of the first subscript
    index (``work_ref.at[dst_plane, ...]`` -> ``"dst_plane"``) or
    ``None`` for whole-ref / dynamically-indexed accesses."""

    __slots__ = ("kind", "ref", "label", "file", "node")

    def __init__(self, kind: str, ref: str, label: Optional[str],
                 file, node: ast.AST) -> None:
        self.kind = kind
        self.ref = ref
        self.label = label
        self.file = file
        self.node = node

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<%s %s[%s] @%d>" % (self.kind, self.ref, self.label,
                                    getattr(self.node, "lineno", 0))


def _region_label(index: ast.AST) -> Optional[str]:
    """The leading-axis tag of a subscript: first tuple element as a
    dotted name or constant; ``None`` when it is computed (a ``pl.ds``
    window, arithmetic, ...) — callers treat ``None`` conservatively."""
    if isinstance(index, ast.Tuple) and index.elts:
        index = index.elts[0]
    if isinstance(index, ast.Constant):
        return str(index.value)
    name = dotted(index)
    return name or None


def _ref_target(node: ast.AST, refs: Dict[str, str]):
    """Decode a ref-view expression to ``(canonical name, label)``:
    a bare ``Name``, ``ref[...]`` or ``ref.at[...]``; ``None`` for
    anything else (scratch refs, semaphores, unrelated values)."""
    if isinstance(node, ast.Name):
        canon = refs.get(node.id)
        return (canon, None) if canon is not None else None
    if isinstance(node, ast.Subscript):
        base = node.value
        if isinstance(base, ast.Attribute) and base.attr == "at":
            base = base.value
        if isinstance(base, ast.Name):
            canon = refs.get(base.id)
            if canon is not None:
                return (canon, _region_label(node.slice))
    return None


def _event_node_key(n: ast.AST):
    # same-line stores sort after loads: ``out[i] = f(in_[i])`` reads
    # the RHS before the store commits, and the textual order would
    # otherwise report a spurious read-after-write on that line
    store = isinstance(n, ast.Subscript) and isinstance(n.ctx, ast.Store)
    return (n.lineno, 1 if store else 0, n.col_offset)


def _ref_events_scan(g: "ProjectGraph", fn: FuncInfo, refs: Dict[str, str],
                     depth: int, seen: Set[int]) -> List[RefEvent]:
    """Ordered read/write events on ``refs`` (local name -> canonical
    name) over ``fn``'s FULL body — nested defs included, because Pallas
    kernels close over their refs in ``fori_loop`` bodies. Calls to
    non-nested project functions that receive a tracked ref positionally
    (or by keyword) are inlined at the call site with the parameter map
    substituted, bounded by ``depth`` and a cycle guard."""
    events: List[RefEvent] = []
    nodes = [n for n in ast.walk(fn.node)
             if isinstance(n, (ast.Subscript, ast.Call, ast.AugAssign))
             and hasattr(n, "lineno")]
    nodes.sort(key=_event_node_key)
    consumed: Set[int] = set()

    def emit(kind: str, dec, node: ast.AST) -> None:
        events.append(RefEvent(kind, dec[0], dec[1], fn.file, node))

    def consume(node: ast.AST) -> None:
        if isinstance(node, ast.Subscript):
            consumed.add(id(node))

    for node in nodes:
        if id(node) in consumed:
            continue
        if isinstance(node, ast.Call):
            tail = dotted(node.func).rsplit(".", 1)[-1]
            if tail == "make_async_copy" and len(node.args) >= 2:
                # pltpu.make_async_copy(src, dst, sem): src read, dst written
                for idx, kind in ((0, "read"), (1, "write")):
                    dec = _ref_target(node.args[idx], refs)
                    if dec is not None:
                        emit(kind, dec, node.args[idx])
                        consume(node.args[idx])
                continue
            if tail in ("load", "store") and node.args:
                dec = _ref_target(node.args[0], refs)
                if dec is not None:
                    emit("read" if tail == "load" else "write", dec, node)
                    consume(node.args[0])
                continue
            if isinstance(node.func, ast.Name) and depth < 3:
                events.extend(_ref_events_call(g, fn, refs, node,
                                               depth, seen))
        elif isinstance(node, ast.AugAssign):
            dec = _ref_target(node.target, refs)
            if dec is not None:
                emit("read", dec, node.target)
                emit("write", dec, node.target)
                consume(node.target)
        elif isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Name):
                dec = _ref_target(node, refs)
                if dec is not None:
                    emit("write" if isinstance(node.ctx, ast.Store)
                         else "read", dec, node)
    return events


def _ref_events_call(g: "ProjectGraph", fn: FuncInfo, refs: Dict[str, str],
                     node: ast.Call, depth: int,
                     seen: Set[int]) -> List[RefEvent]:
    """Inlined events for one bare-name call passing tracked refs."""
    for callee in g.resolve_bare(fn, fn.file.rel, node.func.id):
        cur = callee.parent  # nested defs are already in fn's full walk
        nested = False
        while cur is not None:
            if cur is fn:
                nested = True
                break
            cur = cur.parent
        if nested or callee.node.args.vararg is not None \
                or id(callee) in seen:
            continue
        params = [a.arg for a in callee.node.args.posonlyargs
                  + callee.node.args.args]
        sub: Dict[str, str] = {}
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Starred):
                sub = {}
                break
            if isinstance(a, ast.Name) and a.id in refs and i < len(params):
                sub[params[i]] = refs[a.id]
        for kw in node.keywords:
            if kw.arg is not None and isinstance(kw.value, ast.Name) \
                    and kw.value.id in refs:
                sub[kw.arg] = refs[kw.value.id]
        if sub:
            return _ref_events_scan(g, callee, sub, depth + 1,
                                    seen | {id(callee)})
    return []


def graph_for(project, files: Sequence, key: str) -> ProjectGraph:
    """Build (or fetch the cached) engine over ``files``; the cache lives
    on the Project so every rule of one lint run shares one build."""
    cache = getattr(project, "_graphs", None)
    if cache is None:
        cache = project._graphs = {}
    g = cache.get(key)
    if g is None:
        g = cache[key] = ProjectGraph(files)
    return g
