"""Shared AST helpers for graftlint rules and the dataflow engine.

Pure stdlib. These started life inside ``rules.py`` (PR 4); ISSUE 6 moved
them here so :mod:`.graph` (the interprocedural engine) and the rule
modules can share one vocabulary without import cycles.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set


def dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything dynamic."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return base + "." + node.attr if base else node.attr
    return ""


def import_aliases(tree: ast.Module, nodes=None) -> Dict[str, str]:
    """Local name -> canonical dotted target, from this module's imports
    (``import numpy as np`` -> {'np': 'numpy'}; ``from time import
    perf_counter as pc`` -> {'pc': 'time.perf_counter'})."""
    out: Dict[str, str] = {}
    for node in (ast.walk(tree) if nodes is None else nodes):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = node.module + "." + a.name
    return out


def import_aliases_cached(f) -> Dict[str, str]:
    """``import_aliases`` memoized on the SourceFile: the alias map is
    re-read by several rules and both engine graphs, and the full-tree
    walk behind it is a measurable slice of the <5s lint budget."""
    cached = f.__dict__.get("_lint_aliases")
    if cached is None:
        # the SourceFile already materializes its full node list; reuse
        # it so the alias scan is a list pass, not a second tree walk
        walk = getattr(f, "walk_nodes", None)
        cached = f.__dict__["_lint_aliases"] = import_aliases(
            f.tree, walk() if walk is not None else None)
    return cached


def canonical_call(node: ast.Call, aliases: Dict[str, str]) -> str:
    """The call target's canonical dotted name with the leading import
    alias resolved ('np.asarray' -> 'numpy.asarray')."""
    name = dotted(node.func)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    head = aliases.get(head, head)
    return head + "." + rest if rest else head


def kwarg_names(node: ast.Call) -> Set[str]:
    return {k.arg for k in node.keywords if k.arg is not None}


def is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and not node.args and not node.keywords:
        return dotted(node.func) in {"list", "dict", "set", "bytearray",
                                     "defaultdict", "collections.defaultdict"}
    return False


_OWN_SKIP = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
             ast.Lambda)


def _children(n: ast.AST, out: List[ast.AST]) -> None:
    # manual field iteration: ~2x faster than the iter_child_nodes ->
    # iter_fields generator pair, and own_walk dominates engine profiles
    AST = ast.AST
    for name in n._fields:
        v = getattr(n, name, None)
        if type(v) is list:
            for x in v:
                if isinstance(x, AST):
                    out.append(x)
        elif isinstance(v, AST):
            out.append(v)


def own_walk(node) -> Iterator[ast.AST]:
    """Walk a function's (or module's) OWN statements, not descending into
    nested function/class definitions."""
    stack: List[ast.AST] = []
    _children(node, stack)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _OWN_SKIP):
            continue
        _children(n, stack)


def own_walk_cached(node) -> List[ast.AST]:
    """Materialized :func:`own_walk`, cached on the node itself: both
    engine graph builds and three graph-based rules re-walk the same
    function bodies, and one list beats six generator walks inside the
    <5s full-lint budget (same idiom as ``SourceFile.walk_nodes``)."""
    cached = getattr(node, "_lint_own_walk", None)
    if cached is None:
        cached = node._lint_own_walk = list(own_walk(node))
    return cached


def call_name_args(node: ast.Call) -> Iterator[ast.Name]:
    """Function-valued-looking arguments: bare Name args and kwarg values."""
    for a in list(node.args) + [k.value for k in node.keywords]:
        if isinstance(a, ast.Name):
            yield a
