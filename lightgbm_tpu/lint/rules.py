"""graftlint rule set: this codebase's real hazard classes.

Each rule encodes an invariant that regressed (or nearly regressed) in a
past perf round — see ISSUE 4 / PERF.md. Rules are registered on import
via the :func:`~.core.register` decorator; ``scripts/lint.py --list-rules``
prints this table.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, Project, Rule, SourceFile, register

# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything dynamic."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return base + "." + node.attr if base else node.attr
    return ""


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> canonical dotted target, from this module's imports
    (``import numpy as np`` -> {'np': 'numpy'}; ``from time import
    perf_counter as pc`` -> {'pc': 'time.perf_counter'})."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = node.module + "." + a.name
    return out


def canonical_call(node: ast.Call, aliases: Dict[str, str]) -> str:
    """The call target's canonical dotted name with the leading import
    alias resolved ('np.asarray' -> 'numpy.asarray')."""
    name = dotted(node.func)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    head = aliases.get(head, head)
    return head + "." + rest if rest else head


def _kwarg_names(node: ast.Call) -> Set[str]:
    return {k.arg for k in node.keywords if k.arg is not None}


# ---------------------------------------------------------------------------
# naked-timer
# ---------------------------------------------------------------------------

_TIMER_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.process_time", "time.perf_counter_ns",
                "time.monotonic_ns"}

#: the two modules that IMPLEMENT the trusted-timing discipline
_TIMER_IMPL = {"lightgbm_tpu/obs.py", "lightgbm_tpu/utils/timer.py"}


@register
class NakedTimerRule(Rule):
    """PERF.md measurement discipline: wall clocks must come from
    ``lightgbm_tpu.obs`` (``wall``/``timed_sync`` end in a forced
    1-element transfer; ``block_until_ready`` and bare ``perf_counter``
    pairs do not reliably synchronize through the tunnel)."""

    id = "naked-timer"
    description = ("raw time.time()/perf_counter() wall outside obs.py/"
                   "utils/timer.py; use obs.wall/obs.timed_sync/obs.sync")

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        if f.rel in _TIMER_IMPL:
            return
        aliases = import_aliases(f.tree)
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call) \
                    and canonical_call(node, aliases) in _TIMER_CALLS:
                yield f.finding(node, self.id,
                                "naked wall-clock timer %s(); use "
                                "lightgbm_tpu.obs (wall/timed_sync/sync)"
                                % dotted(node.func))


# ---------------------------------------------------------------------------
# host-sync (cross-file: call graph over the traced hot modules)
# ---------------------------------------------------------------------------

_HOT_FILES = ("lightgbm_tpu/learner.py", "lightgbm_tpu/fused.py")
_HOT_DIRS = ("lightgbm_tpu/ops/", "lightgbm_tpu/serve/")

_SYNC_ATTR_CALLS = {"item", "tolist", "block_until_ready"}
_SYNC_DOTTED = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
                "jax.device_get"}
_SYNC_BUILTINS = {"float", "int"}

_JIT_HEADS = {"jax.jit", "jit"}
_PARTIAL_HEADS = {"partial", "functools.partial", "_partial"}


class _FnInfo:
    __slots__ = ("node", "file", "qual", "parent", "is_method", "children",
                 "hot", "edges")

    def __init__(self, node, file: SourceFile, qual: str,
                 parent: Optional["_FnInfo"], is_method: bool) -> None:
        self.node = node
        self.file = file
        self.qual = qual
        self.parent = parent
        self.is_method = is_method
        self.children: Dict[str, List["_FnInfo"]] = {}
        self.hot = False
        self.edges: List["_FnInfo"] = []


def _is_jit_decorator(dec: ast.AST) -> bool:
    name = dotted(dec)
    if name in _JIT_HEADS or name.endswith(".jit"):
        return True
    if isinstance(dec, ast.Call):
        fname = dotted(dec.func)
        if fname in _JIT_HEADS or fname.endswith(".jit"):
            return True
        if fname in _PARTIAL_HEADS or fname.endswith(".partial"):
            return any(dotted(a) in _JIT_HEADS or dotted(a).endswith(".jit")
                       for a in dec.args)
    return False


def _own_walk(node) -> Iterator[ast.AST]:
    """Walk a function's (or module's) OWN statements, not descending into
    nested function/class definitions."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _call_name_args(node: ast.Call) -> Iterator[ast.Name]:
    for a in list(node.args) + [k.value for k in node.keywords]:
        if isinstance(a, ast.Name):
            yield a


@register
class HostSyncRule(Rule):
    """No host-device syncs inside functions reachable from the traced hot
    phases (the round-5 dispatch-soup class: one stray ``.item()`` or
    ``np.asarray`` in the per-split loop serializes the pipeline).

    Reachability is a lexically-scoped call graph over learner.py,
    fused.py, ops/ and serve/: entries are jit-decorated functions and functions
    wrapped by value in ``jax.jit``/``partial`` (the learner hands
    ``partial(build_tree*, ...)`` to jit); edges follow bare-name calls
    (resolved innermost-scope-first, never to methods), ``x.attr(...)``
    calls (resolved to methods only), function-valued arguments (covers
    ``lax.while_loop``/``scan``/``vmap`` bodies), and nested defs of hot
    functions. ``float()``/``int()`` are flagged only when the argument
    visibly involves a jax/jnp call — static config scalars stay legal."""

    id = "host-sync"
    description = (".item()/float()/np.asarray/block_until_ready inside "
                   "functions reachable from jit-traced hot phases")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hot_files = [f for f in project.files
                     if f.tree is not None
                     and (f.rel in _HOT_FILES or f.rel.startswith(_HOT_DIRS))]
        if not hot_files:
            return
        infos: List[_FnInfo] = []
        methods: Dict[str, List[_FnInfo]] = {}
        top_level: Dict[str, Dict[str, List[_FnInfo]]] = {}  # rel -> name -> fns

        # pass 1: collect functions with their lexical position
        for f in hot_files:
            top_level[f.rel] = {}
            stack: List[Tuple[ast.AST, str, Optional[_FnInfo], bool]] = \
                [(f.tree, "", None, False)]
            while stack:
                parent, prefix, encl, in_class = stack.pop()
                for node in ast.iter_child_nodes(parent):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        info = _FnInfo(node, f, prefix + node.name, encl,
                                       in_class)
                        infos.append(info)
                        if in_class:
                            methods.setdefault(node.name, []).append(info)
                        elif encl is None:
                            top_level[f.rel].setdefault(
                                node.name, []).append(info)
                        else:
                            encl.children.setdefault(
                                node.name, []).append(info)
                        stack.append((node, info.qual + ".", info, False))
                    elif isinstance(node, ast.ClassDef):
                        stack.append((node, prefix + node.name + ".",
                                      encl, True))
                    else:
                        stack.append((node, prefix, encl, in_class))

        def resolve_bare(ctx: Optional[_FnInfo], rel: str, name: str
                         ) -> List[_FnInfo]:
            cur = ctx
            while cur is not None:
                if name in cur.children:
                    return cur.children[name]
                cur = cur.parent
            if name in top_level.get(rel, {}):
                return top_level[rel][name]
            out = []
            for tl in top_level.values():
                out.extend(tl.get(name, []))
            return out

        # pass 2: entries (decorators + jit/partial by value) and edges
        entries: List[_FnInfo] = []
        for info in infos:
            if any(_is_jit_decorator(d) for d in info.node.decorator_list):
                entries.append(info)

        alias_cache: Dict[str, Dict[str, str]] = {}

        def scan_calls(owner: Optional[_FnInfo], f: SourceFile, body):
            rel = f.rel
            if rel not in alias_cache:
                alias_cache[rel] = import_aliases(f.tree)
            aliases = alias_cache[rel]
            for node in _own_walk(body):
                if not isinstance(node, ast.Call):
                    continue
                cname = canonical_call(node, aliases)
                wraps = (cname in _JIT_HEADS or cname.endswith(".jit")
                         or cname in _PARTIAL_HEADS)
                for a in _call_name_args(node):
                    for target in resolve_bare(owner, rel, a.id):
                        if wraps:
                            entries.append(target)
                        elif owner is not None:
                            owner.edges.append(target)
                if owner is None:
                    continue
                fn = node.func
                if isinstance(fn, ast.Name):
                    owner.edges.extend(resolve_bare(owner, rel, fn.id))
                elif isinstance(fn, ast.Attribute):
                    owner.edges.extend(methods.get(fn.attr, []))

        for f in hot_files:
            scan_calls(None, f, f.tree)
        for info in infos:
            scan_calls(info, info.file, info.node)

        # pass 3: propagate hotness (nested defs trace with their parent)
        work = list(entries)
        for info in work:
            info.hot = True
        while work:
            cur = work.pop()
            for group in cur.children.values():
                cur.edges.extend(group)
            for nxt in cur.edges:
                if not nxt.hot:
                    nxt.hot = True
                    work.append(nxt)

        # pass 4: scan hot bodies (own statements only; nested defs are
        # scanned as their own hot entries)
        for info in infos:
            if not info.hot:
                continue
            if info.file.rel not in alias_cache:
                alias_cache[info.file.rel] = import_aliases(info.file.tree)
            aliases = alias_cache[info.file.rel]
            for node in _own_walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._sync_kind(node, aliases)
                if hit:
                    yield info.file.finding(
                        node, self.id,
                        "%s in '%s', reachable from a jit-traced hot "
                        "phase (forces a host-device sync)"
                        % (hit, info.qual))

    @staticmethod
    def _arg_is_arrayish(node: ast.AST, aliases: Dict[str, str]) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                head = canonical_call(n, aliases).split(".")[0]
                if head in ("jax", "jnp") or aliases.get(head) == "jax.numpy":
                    return True
        return False

    @classmethod
    def _sync_kind(cls, node: ast.Call,
                   aliases: Dict[str, str]) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTR_CALLS \
                and not node.args and not node.keywords:
            return ".%s()" % fn.attr
        cname = canonical_call(node, aliases)
        if cname in _SYNC_DOTTED:
            return "%s()" % dotted(node.func)
        if cname in _SYNC_BUILTINS and node.args \
                and cls._arg_is_arrayish(node.args[0], aliases):
            return "%s() conversion" % cname
        return None


# ---------------------------------------------------------------------------
# implicit-dtype
# ---------------------------------------------------------------------------

#: constructor -> index of the positional dtype parameter
_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "arange": 3,
              "asarray": 1}
_JNP_HEADS = {"jax.numpy", "jnp"}


@register
class ImplicitDtypeRule(Rule):
    """ops/ kernels must spell dtypes out: implicit f32/i32 promotion
    changed bit patterns across jax versions and hid u8-vs-i32 traffic
    regressions; golden/consistency tests pin the explicit choice."""

    id = "implicit-dtype"
    description = ("jnp.zeros/ones/empty/full/arange/asarray without an "
                   "explicit dtype in lightgbm_tpu/ops/ kernels")

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        if not f.rel.startswith("lightgbm_tpu/ops/"):
            return
        aliases = import_aliases(f.tree)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = canonical_call(node, aliases)
            head, _, tail = cname.rpartition(".")
            if head not in _JNP_HEADS and aliases.get(head, head) != "jax.numpy":
                continue
            pos = _DTYPE_POS.get(tail)
            if pos is None:
                continue
            if "dtype" in _kwarg_names(node) or len(node.args) > pos:
                continue
            yield f.finding(node, self.id,
                            "%s without an explicit dtype" % dotted(node.func))


# ---------------------------------------------------------------------------
# unnamed-pallas-call
# ---------------------------------------------------------------------------

@register
class UnnamedPallasCallRule(Rule):
    """``pallas_call`` without ``name=`` drops the kernel's identity from
    profiler timelines and HLO dumps — PR 3's phase tracing (and every
    trace-driven bisect script) keys on those names."""

    id = "unnamed-pallas-call"
    description = "pallas_call without a name= (breaks phase tracing)"

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call) \
                    and dotted(node.func).rsplit(".", 1)[-1] == "pallas_call" \
                    and "name" not in _kwarg_names(node):
                yield f.finding(node, self.id,
                                "pallas_call without name= (kernel is "
                                "anonymous in traces and HLO dumps)")


# ---------------------------------------------------------------------------
# mutable-default
# ---------------------------------------------------------------------------

def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and not node.args and not node.keywords:
        return dotted(node.func) in {"list", "dict", "set", "bytearray",
                                     "defaultdict", "collections.defaultdict"}
    return False


@register
class MutableDefaultRule(Rule):
    """Mutable default arguments are shared across calls — with cached
    jitted callables (``_BLOCK_CACHE``) a leaked default outlives the
    Booster that wrote it."""

    id = "mutable-default"
    description = "mutable default argument (list/dict/set literal)"

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                args = node.args
                for d in list(args.defaults) + [
                        d for d in args.kw_defaults if d is not None]:
                    if _is_mutable_literal(d):
                        yield f.finding(
                            d, self.id,
                            "mutable default argument in '%s'"
                            % getattr(node, "name", "<lambda>"))


# ---------------------------------------------------------------------------
# module-mutable-state
# ---------------------------------------------------------------------------

_MUTATOR_METHODS = {"append", "add", "update", "setdefault", "pop",
                    "popitem", "clear", "extend", "insert", "remove",
                    "discard"}


@register
class ModuleMutableStateRule(Rule):
    """Module-level mutable state written from function scope is a hidden
    process-global — telemetry belongs in the ``obs`` registry (locked,
    snapshot-able, reset-able), not in ad-hoc module dicts. Deliberate
    caches carry an inline disable naming their invariant."""

    id = "module-mutable-state"
    description = ("module-level mutable literal written from function "
                   "scope outside the obs registry")

    def check_file(self, f: SourceFile) -> Iterator[Finding]:
        if f.rel == "lightgbm_tpu/obs.py":
            return
        decls: Dict[str, ast.stmt] = {}
        for node in f.tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target = node.targets[0].id
                value = node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target = node.target.id
                value = node.value
            if target and _is_mutable_literal(value):
                decls[target] = node
        if not decls:
            return
        writes: Dict[str, Tuple[int, str]] = {}

        def visit_fn(fn_node):
            for node in ast.walk(fn_node):
                name, how = None, ""
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in decls:
                            name, how = t.value.id, "subscript write"
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in decls \
                        and node.func.attr in _MUTATOR_METHODS:
                    name, how = node.func.value.id, \
                        ".%s()" % node.func.attr
                elif isinstance(node, ast.Global):
                    for n in node.names:
                        if n in decls:
                            name, how = n, "global rebind"
                if name and name not in writes:
                    writes[name] = (node.lineno, how)

        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_fn(node)
        for name, decl in decls.items():
            if name in writes:
                line, how = writes[name]
                yield f.finding(
                    decl, self.id,
                    "module-level mutable '%s' written from function scope "
                    "(%s at line %d); use the obs registry or justify with "
                    "an inline disable" % (name, how, line))
