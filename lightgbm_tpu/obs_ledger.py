"""Persistent run ledger: append-only JSONL of what each run cost and
how its ``auto`` knobs resolved.

The ROADMAP self-calibration item: auto-knob resolutions (recorded in
telemetry since PR 3) evaporate with the process, so every fresh train on
the same machine re-derives the same answers — and nothing persists what
a run *cost*, so regressions are only caught while someone is watching a
bench. The ledger fixes both with one file:

- :func:`record_run` appends ONE JSON line per train/bench/serve run:
  machine identity (host, backend, device kind/count), dataset shape,
  a config fingerprint, every resolved auto knob, a compact telemetry
  snapshot (counters/timers/compiles) and the device-cost section.
- :func:`preresolve` reads the newest entry matching the current
  (machine, dataset-shape, config) key and hands its resolved ``tpu_*``
  knobs back to the learner, which applies them INSTEAD of re-running
  auto resolution — a machine tunes itself once, then every later run
  starts pre-resolved (zero new ``auto_resolution`` records; pinned in
  tests/test_ledger.py).
- ``scripts/ledger.py`` adds list/show/compare/gate CLI modes over the
  same file; ``scripts/check.sh --ledger`` wires the gate into CI.

Format notes: JSONL so appends are atomic-enough under POSIX (one
``write`` of one line), the file is greppable, and partial/corrupt lines
(a killed process mid-append) are skipped on read, never fatal. The
module is import-light — no jax at import time — so ``scripts/ledger.py``
can query a ledger on machines without an accelerator stack.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .utils.log import Log

#: schema version stamped on every entry; readers skip newer majors
LEDGER_VERSION = 1

#: config fields excluded from the fingerprint: paths, dump targets and
#: report knobs that vary between otherwise-identical runs, the ledger's
#: own knobs (turning the ledger on must match entries recorded before),
#: and data/valid (the dataset is keyed by SHAPE, not by path — the same
#: matrix under a renamed file should still match)
_FP_SKIP = frozenset({
    "data", "valid", "input_model", "output_model", "output_result",
    "convert_model", "convert_model_language", "verbosity",
    "dump_telemetry", "dump_trace", "telemetry_dump_interval_s",
    "snapshot_freq", "saved_feature_importance_type",
    "obs_ledger", "obs_ledger_path", "obs_device_cost",
    "obs_check_finite", "obs_hbm_sample_interval_s",
})

#: auto-knob prefix eligible for preresolution (ISSUE: "pre-resolves
#: tpu_* auto knobs"); everything else in an entry is reporting-only
_PRERESOLVE_PREFIX = "tpu_"


def config_fingerprint(config) -> str:
    """Stable hash of every perf-relevant config field (see _FP_SKIP).

    The AUTO values are hashed, not the resolved ones — a run that was
    pre-resolved from the ledger must produce the same fingerprint as the
    run that recorded the entry, or the key would drift after one hop.
    The learner guarantees this by never mutating the Config object.
    """
    parts: List[str] = []
    for f in dataclasses.fields(config):
        if f.name in _FP_SKIP or f.name.startswith("_"):
            continue
        parts.append("%s=%r" % (f.name, getattr(config, f.name)))
    return hashlib.sha1(";".join(parts).encode()).hexdigest()[:16]


def machine_identity() -> Dict[str, Any]:
    """Host + accelerator identity. jax is imported lazily and a missing
    or broken backend degrades to host-only identity (the CLI must be
    able to stamp entries on a query-only machine)."""
    ident: Dict[str, Any] = {"host": socket.gethostname()}
    try:
        import jax
        devs = jax.local_devices()
        ident["backend"] = jax.default_backend()
        ident["device_kind"] = devs[0].device_kind if devs else "none"
        ident["device_count"] = len(devs)
    except Exception:
        ident["backend"] = "unavailable"
        ident["device_kind"] = "none"
        ident["device_count"] = 0
    return ident


def _machine_key(ident: Dict[str, Any]) -> List[Any]:
    # hostname intentionally NOT in the match key: "same machine" for
    # knob resolution means same accelerator, not same DNS name — a
    # ledger shipped between identical v5e hosts should still hit
    return [ident.get("backend"), ident.get("device_kind"),
            ident.get("device_count")]


def resolved_knobs() -> Dict[str, Any]:
    """Every auto-knob resolution of the CURRENT process, merged from the
    live telemetry records: fresh resolutions (``auto_resolution``) and
    ledger-applied ones (``ledger_preresolution``) — so an entry written
    by a pre-resolved run still carries the full knob set forward."""
    from .obs import telemetry
    knobs: Dict[str, Any] = {}
    for name in ("auto_resolution", "ledger_preresolution"):
        for rec in telemetry.records(name):
            k, v = rec.get("knob"), rec.get("value")
            if k:
                knobs[str(k)] = v
    return knobs


def build_entry(config, kind: str, rows: int, features: int,
                extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble one ledger entry from the process's current telemetry.
    Pure read — does not touch the ledger file."""
    from .obs import telemetry
    snap = telemetry.snapshot()
    entry = {
        "v": LEDGER_VERSION,
        "ts": time.time(),   # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        "kind": kind,                      # train | bench | serve
        "machine": machine_identity(),
        "dataset": {"rows": int(rows), "features": int(features)},
        "config_fp": config_fingerprint(config),
        "resolved_knobs": resolved_knobs(),
        "telemetry": {
            "counters": snap.get("counters", {}),
            "timers": snap.get("timers", {}),
            "jit_compiles": snap.get("jit_compiles", {}),
        },
        "device_cost": snap.get("device_cost", {}),
    }
    if extra:
        entry["extra"] = dict(extra)
    return entry


def append_jsonl(path: str, entry: Dict[str, Any]) -> None:
    """The durable-append substrate (shared with ``lightgbm_tpu.fleet``):
    one entry as one JSONL line written in ONE write call — atomic-enough
    under POSIX appends, so concurrent writers interleave whole lines and
    a killed process leaves at most one partial line (skipped on read).
    Creates the file and parent directory on first use."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")


def read_jsonl(path: str,
               max_version: Optional[int] = None) -> Iterator[Dict[str, Any]]:
    """Yield dict lines oldest-first, skipping blank/corrupt/partial
    lines (a killed writer mid-append must never poison the file) and —
    when ``max_version`` is given — entries whose ``v`` field is newer
    than the reader understands."""
    if not os.path.exists(path):
        return
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if not isinstance(e, dict):
                continue
            if max_version is not None and e.get("v", 0) > max_version:
                continue
            yield e


def append(path: str, entry: Dict[str, Any]) -> None:
    """Append one ledger entry (see :func:`append_jsonl`)."""
    append_jsonl(path, entry)


def record_run(config, kind: str, rows: int, features: int,
               extra: Optional[Dict[str, Any]] = None) -> Optional[dict]:
    """build_entry + append to ``config.obs_ledger_path``. Never raises:
    a read-only filesystem must not fail the training run it describes."""
    try:
        entry = build_entry(config, kind, rows, features, extra)
        append(config.obs_ledger_path, entry)
        from .obs import telemetry
        telemetry.count("ledger/entries_written")
        return entry
    except Exception as exc:
        Log.warning("ledger append failed (%s): %s",
                    type(exc).__name__, exc)
        return None


def read_entries(path: str) -> Iterator[Dict[str, Any]]:
    """Yield entries oldest-first; corrupt/partial lines and newer-major
    entries are skipped (counted nowhere — the CLI reports them)."""
    yield from read_jsonl(path, max_version=LEDGER_VERSION)


def _match(entry: Dict[str, Any], machine_key: List[Any], rows: int,
           features: int, config_fp: str, kind: Optional[str]) -> bool:
    ds = entry.get("dataset", {})
    return (
        _machine_key(entry.get("machine", {})) == machine_key
        and ds.get("rows") == rows and ds.get("features") == features
        and entry.get("config_fp") == config_fp
        and (kind is None or entry.get("kind") == kind)
    )


def find_matching(path: str, config, rows: int, features: int,
                  kind: Optional[str] = None,
                  n: Optional[int] = None) -> List[Dict[str, Any]]:
    """Entries matching the (machine, shape, config) key, oldest-first;
    ``n`` keeps only the newest n."""
    key = _machine_key(machine_identity())
    fp = config_fingerprint(config)
    out = [e for e in read_entries(path)
           if _match(e, key, int(rows), int(features), fp, kind)]
    return out[-n:] if n else out


def preresolve(config, rows: int, features: int) -> Dict[str, Any]:
    """The resolved ``tpu_*`` knobs of the newest matching entry, or {}.

    The learner consults this once per build (when ``obs_ledger`` is on)
    and applies the values to knobs still set to auto — skipping its own
    resolution records for them, which is how the acceptance test
    observes "zero new auto_resolution records" on the second run.
    Returns {} on any problem: preresolution is an optimization, a
    corrupt ledger must never block a train."""
    try:
        matches = find_matching(config.obs_ledger_path, config,
                                rows, features, n=1)
    except Exception as exc:
        Log.warning("ledger preresolve failed (%s): %s",
                    type(exc).__name__, exc)
        return {}
    if not matches:
        return {}
    knobs = matches[-1].get("resolved_knobs", {})
    return {k: v for k, v in knobs.items()
            if k.startswith(_PRERESOLVE_PREFIX)}


# ---------------------------------------------------------------------------
# Query / compare / gate (the scripts/ledger.py backend)
# ---------------------------------------------------------------------------

def metric_value(entry: Dict[str, Any], metric: str) -> Optional[float]:
    """Dotted-path lookup (``extra.train_s``, ``telemetry.timers.fused/
    device_wait``) returning a float or None. Path components are split
    on the FIRST dots only until a dict key containing dots matches —
    timer names contain '/', not '.', so plain split is unambiguous."""
    node: Any = entry
    for part in metric.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    try:
        return float(node)
    except (TypeError, ValueError):
        return None


def compare(a: Dict[str, Any], b: Dict[str, Any],
            metrics: List[str]) -> List[Tuple[str, Optional[float],
                                              Optional[float]]]:
    """[(metric, value_in_a, value_in_b)] for reporting."""
    return [(m, metric_value(a, m), metric_value(b, m)) for m in metrics]


def gate(path: str, config, rows: int, features: int, metric: str,
         tolerance: float, kind: Optional[str] = None) -> Tuple[bool, str]:
    """Regression gate over the newest two matching entries: fail when
    the newest is more than ``tolerance`` (fractional) worse than the
    previous on ``metric`` (lower is better — the gated metrics are
    times/bytes). Passes with an explanatory message when fewer than two
    matching entries exist (first run on a machine must not fail CI)."""
    matches = find_matching(path, config, rows, features, kind=kind, n=2)
    if len(matches) < 2:
        return True, ("ledger gate: %d matching entr%s at %s — nothing to "
                      "compare, pass" % (len(matches),
                                         "y" if len(matches) == 1 else "ies",
                                         path))
    prev, cur = matches[-2], matches[-1]
    pv, cv = metric_value(prev, metric), metric_value(cur, metric)
    if pv is None or cv is None:
        return True, ("ledger gate: metric %r missing (prev=%r cur=%r) — "
                      "pass" % (metric, pv, cv))
    if pv <= 0:
        return True, "ledger gate: previous %s=%g not positive — pass" % (
            metric, pv)
    ratio = cv / pv
    msg = "ledger gate: %s prev=%.6g cur=%.6g ratio=%.3f tolerance=%.2f" % (
        metric, pv, cv, ratio, 1.0 + tolerance)
    return ratio <= 1.0 + tolerance, msg
