"""Multi-host training entry (reference analog: the Dask layer,
python-package/lightgbm/dask.py:56,333, and the CLI's machine-list network
init, application.cpp:168).

On TPU pods the reference's socket/MPI bootstrap collapses into JAX's
multi-host runtime: every host runs the same program, calls
``init_distributed()`` once (jax.distributed.initialize discovers peers
from the TPU metadata or the explicit coordinator address), and trains with
``tree_learner=data|voting`` over the GLOBAL device mesh — XLA routes the
histogram collectives over ICI within a slice and DCN across slices.
There is no Dask scheduler, no machine list, no open-port probing
(dask.py:56 _find_open_port): process placement is the platform's job.

Typical pod usage::

    import lightgbm_tpu as lgb
    from lightgbm_tpu.parallel.distributed import init_distributed, global_mesh

    init_distributed()                       # once per host process
    with global_mesh():
        bst = lgb.train({"tree_learner": "data", ...}, dset)

Every host must construct the same Dataset (pre-sharding rows by host is
unnecessary: the mesh shards rows across all global devices).
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import jax

from ..utils.log import Log
from .mesh import make_mesh


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Initialize the JAX multi-host runtime (idempotent).

    With no arguments, platform auto-detection applies (TPU pod metadata /
    cloud environment variables) — the analog of the reference reading
    ``machines``/``num_machines`` (config.h) before Network::Init.
    """
    if jax.process_count() > 1 or getattr(init_distributed, "_done", False):
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        init_distributed._done = True
        Log.info("distributed: process %d of %d, %d global devices",
                 jax.process_index(), jax.process_count(),
                 len(jax.devices()))
    except Exception as e:
        # FAIL LOUDLY: a mis-bootstrapped host silently training on its
        # local devices would run different collectives than its peers
        # (the reference likewise aborts in Network::Init,
        # src/network/linkers_socket.cpp, when the cluster is short)
        Log.fatal("jax.distributed.initialize failed: %s. Fix the "
                  "coordinator/num_processes/process_id bootstrap or run "
                  "single-host by not calling init_distributed.", e)


@contextmanager
def global_mesh(n_devices: Optional[int] = None):
    """A 1-D data mesh over ALL global devices (multi-host aware)."""
    mesh = make_mesh(n_devices)
    with mesh:
        yield mesh
