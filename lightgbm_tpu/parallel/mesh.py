"""Device mesh + distributed (data-parallel) tree learner.

TPU-native equivalent of the reference's distributed tree learners and
Network layer (reference: src/treelearner/data_parallel_tree_learner.cpp,
feature_parallel_tree_learner.cpp, voting_parallel_tree_learner.cpp;
src/network/network.cpp). The mapping (SURVEY.md §2.3):

- machine list / sockets / MPI  ->  ``jax.sharding.Mesh`` over a 1-D
  ``data`` axis; XLA owns routing over ICI/DCN, no topology maps.
- per-leaf histogram ReduceScatter + best-split allgather
  (data_parallel_tree_learner.cpp:155-251)  ->  ``lax.psum`` of the
  (F, B, 3) histogram inside ``shard_map``. Because the full split search
  is replicated-cheap (O(F·B)) on TPU, the reduce-scatter + argmax-sync
  two-step collapses into one psum; the feature-parallel and
  voting-parallel learners' comm-volume optimizations become Pallas/async
  refinements of the same seam rather than separate code paths.
- rank row-partition (pre_partition)  ->  row sharding of the binned
  matrix: ``NamedSharding(mesh, P('data'))``.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..dataset import BinnedDataset
from ..learner import Comm, SerialTreeLearner, TreeLog

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (DATA_AXIS,))


def round_up(n: int, d: int) -> int:
    return ((n + d - 1) // d) * d


class DataParallelTreeLearner(SerialTreeLearner):
    """Row-sharded learner: bins and (g,h,cnt) live sharded over the mesh;
    one tree grows with psum'd histograms (reference analog:
    DataParallelTreeLearner, tree_learner=data)."""

    def __init__(self, config: Config, dataset: BinnedDataset, mesh: Mesh) -> None:
        super().__init__(config, dataset, comm_axis=DATA_AXIS)
        self.mesh = mesh
        d = mesh.devices.size
        n = dataset.num_data
        self.padded_n = round_up(n, d)
        bins_np = np.asarray(dataset.binned)
        if self.padded_n != n:
            bins_np = np.pad(bins_np, ((0, self.padded_n - n), (0, 0)))
        self.row_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self.rep_sharding = NamedSharding(mesh, P())
        self.bins = jax.device_put(jnp.asarray(bins_np), self.row_sharding)

        inner = self.make_build_fn()
        sharded = jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(), P()),
            out_specs=TreeLog(
                num_splits=P(), split_leaf=P(), feature=P(), bin=P(), kind=P(),
                default_left=P(), gain=P(), left_sum=P(), right_sum=P(),
                go_left=P(), miss_bin=P(), movable=P(), leaf_value=P(),
                leaf_sum=P(), row_leaf=P(DATA_AXIS)),
            check_vma=False,
        )
        self._build = jax.jit(sharded)

    def train(self, ghc: jax.Array, feature_mask: jax.Array, key: jax.Array) -> TreeLog:
        n = self.dataset.num_data
        if self.padded_n != n:
            ghc = jnp.pad(ghc, ((0, self.padded_n - n), (0, 0)))
        ghc = jax.device_put(ghc, self.row_sharding)
        log = self._build(self.bins, ghc, self.meta, feature_mask, key)
        if self.padded_n != n:
            log = log._replace(row_leaf=log.row_leaf[:n])
        return log


def create_tree_learner(config: Config, dataset: BinnedDataset,
                        mesh: Optional[Mesh] = None) -> SerialTreeLearner:
    """Factory (reference: src/treelearner/tree_learner.cpp:15
    CreateTreeLearner). ``serial`` = single device; ``data``/``feature``/
    ``voting`` = row-sharded mesh learner (feature- and voting-parallel
    specializations share the psum seam; their comm-volume tricks are
    device-side optimizations on TPU, not separate partitionings)."""
    if config.tree_learner == "serial" or mesh is None or mesh.devices.size <= 1:
        return SerialTreeLearner(config, dataset)
    return DataParallelTreeLearner(config, dataset, mesh)
