"""Device mesh + distributed tree learners (data / feature / voting).

TPU-native equivalent of the reference's distributed tree learners and
Network layer (reference: src/treelearner/data_parallel_tree_learner.cpp,
feature_parallel_tree_learner.cpp, voting_parallel_tree_learner.cpp;
src/network/network.cpp). The mapping (SURVEY.md §2.3):

- machine list / sockets / MPI  ->  ``jax.sharding.Mesh`` over a 1-D
  ``data`` axis; XLA owns routing over ICI/DCN, no topology maps.
- the reference's 4x3 learner-type x device matrix collapses to ONE
  builder (learner.build_tree_partitioned) parameterized by a ``Comm``
  strategy (learner.Comm):
  * data-parallel: rows sharded, per-leaf histograms psum'd, every shard
    derives the same split (histogram ReduceScatter + best-split argmax
    sync fold into one collective, data_parallel_tree_learner.cpp:155-251).
    Comm per split round: one (G, Bm, 3) f32 allreduce of the smaller
    child's histogram.
  * feature-parallel: rows replicated, the split SEARCH is sharded by
    feature ownership and only the winning SplitInfo is argmax-synced
    (feature_parallel_tree_learner.cpp:40-84; SyncUpGlobalBestSplit,
    parallel_tree_learner.h:191). Comm per round: O(B) — one SplitInfo.
  * voting-parallel: rows sharded, histograms stay LOCAL; shards vote
    their top-k features, the global top-2k features' histograms are
    merged and searched (voting_parallel_tree_learner.cpp:151
    GlobalVoting / PV-Tree). Comm per round: O(F) vote counts +
    O(2*top_k * Bm * 3) merged rows — bounded as F grows.
- rank row-partition (pre_partition)  ->  row sharding of the binned
  matrix: ``NamedSharding(mesh, P('data'))``.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..dataset import BinnedDataset
from ..learner import Comm, SerialTreeLearner, TreeLog
from ..obs import track_jit
from ..utils.log import Log

DATA_AXIS = "data"


def _shard_map(fn, *, mesh, in_specs, out_specs):
    # jax >= 0.6 exposes shard_map at top level (check_vma); older releases
    # only have the experimental module (check_rep). Replication checking is
    # off either way: the learners do their own collectives through Comm.
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (DATA_AXIS,))


def round_up(n: int, d: int) -> int:
    return ((n + d - 1) // d) * d


def _tree_log_specs(row_spec: P) -> TreeLog:
    return TreeLog(
        num_splits=P(), split_leaf=P(), feature=P(), bin=P(), kind=P(),
        default_left=P(), gain=P(), left_sum=P(), right_sum=P(),
        go_left=P(), miss_bin=P(), movable=P(), leaf_value=P(),
        leaf_sum=P(), row_leaf=row_spec)


class _MeshTreeLearner(SerialTreeLearner):
    """Shared shard_map wiring for the distributed learners."""

    comm_mode = "data"
    rows_sharded = True

    def __init__(self, config: Config, dataset: BinnedDataset,
                 mesh: Mesh) -> None:
        self.mesh = mesh
        super().__init__(config, dataset, comm_axis=DATA_AXIS)
        n = dataset.num_data
        d = mesh.devices.size
        self.row_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self.rep_sharding = NamedSharding(mesh, P())
        if self.rows_sharded:
            shard = getattr(dataset, "shard_info", None)
            if shard is not None and jax.process_count() > 1:
                # distributed loading: every process holds only its row
                # shard; assemble the global sharded array without any host
                # ever materializing the full matrix (reference analog: the
                # per-rank partitions of dataset_loader.cpp:951)
                rank, world, n_total = shard
                if world != jax.process_count():
                    Log.fatal("dataset was sharded for %d processes but "
                              "%d are running", world, jax.process_count())
                self.padded_n = round_up(n_total, d)
                local = np.asarray(dataset.binned)
                per_proc = self.padded_n // world
                if len(local) != per_proc:
                    pad_rows = per_proc - len(local)
                    if pad_rows < 0:
                        Log.fatal("shard %d has %d rows > %d per-process "
                                  "capacity", rank, len(local), per_proc)
                    local = np.pad(local, ((0, pad_rows), (0, 0)))
                self.bins = jax.make_array_from_process_local_data(
                    self.row_sharding, local)
            else:
                self.padded_n = round_up(n, d)
                bins_np = np.asarray(dataset.binned)
                if self.padded_n != n:
                    bins_np = np.pad(bins_np,
                                     ((0, self.padded_n - n), (0, 0)))
                self.bins = jax.device_put(jnp.asarray(bins_np),
                                           self.row_sharding)
            row_spec = P(DATA_AXIS)
        else:
            self.padded_n = n
            self.bins = jax.device_put(self.bins, self.rep_sharding)
            row_spec = P()

        if self.comm_mode != "data" and not self.use_partition():
            Log.fatal("tree_learner=%s requires the partitioned builder "
                      "(max_bin <= 256)", self.comm_mode)
        inner = self.make_build_fn()
        data_spec = P(DATA_AXIS) if self.rows_sharded else P()
        sharded = _shard_map(
            inner, mesh=mesh,
            in_specs=(data_spec, data_spec, P(), P(), P(), P()),
            out_specs=_tree_log_specs(row_spec),
        )
        self._build = track_jit("mesh/build", jax.jit(sharded))

    def _make_comm(self, axis: Optional[str]) -> Comm:
        return Comm(axis, mode=self.comm_mode,
                    top_k=int(self.config.top_k),
                    num_machines=int(self.mesh.devices.size),
                    hist_scatter=bool(self.config.tpu_hist_scatter))

    def train(self, ghc: jax.Array, feature_mask: jax.Array, key: jax.Array,
              cegb_used=None) -> TreeLog:
        n = self.dataset.num_data
        if cegb_used is None:
            cegb_used = jnp.zeros((self.dataset.num_features,), bool)
        shard = getattr(self.dataset, "shard_info", None)
        multiproc = self.rows_sharded and shard is not None \
            and jax.process_count() > 1
        if multiproc:
            # the dataset holds only this process's rows: gradients must be
            # assembled the same way the bins were — each process
            # contributes its LOCAL rows to the global row-sharded array
            # (device_put would instead scatter the local array as if it
            # were the global one, pairing rank>0 bins with garbage)
            per_proc = self.padded_n // shard[1]
            loc = np.asarray(ghc)
            if len(loc) != per_proc:
                loc = np.pad(loc, ((0, per_proc - len(loc)), (0, 0)))
            ghc = jax.make_array_from_process_local_data(
                self.row_sharding, loc)
        elif self.rows_sharded and self.padded_n != n:
            ghc = jnp.pad(ghc, ((0, self.padded_n - n), (0, 0)))
        sharding = self.row_sharding if self.rows_sharded else self.rep_sharding
        if not multiproc:
            ghc = jax.device_put(ghc, sharding)
        log = self._build(self.bins, ghc, self.meta, feature_mask, key,
                          cegb_used)
        if multiproc:
            # row_leaf comes back globally sharded; this process's score
            # updates need only its LOCAL rows. Collect the addressable
            # shards onto one local device and concatenate THERE — the
            # previous np.asarray round-trip moved O(local rows) through
            # the host on EVERY tree
            dev0 = jax.local_devices()[0]
            rows = jnp.concatenate(
                [jax.device_put(sh.data, dev0)
                 for sh in sorted(log.row_leaf.addressable_shards,
                                  key=lambda sh: sh.index[0].start or 0)])
            # leaf_value is consumed by the process-local score update: a
            # globally-replicated array cannot mix with the single-device
            # score (it is tiny — a host hop is fine)
            log = log._replace(
                row_leaf=rows[:n],
                leaf_value=jax.device_put(np.asarray(log.leaf_value), dev0))
        elif self.rows_sharded and self.padded_n != n:
            log = log._replace(row_leaf=log.row_leaf[:n])
        return log


class DataParallelTreeLearner(_MeshTreeLearner):
    """tree_learner=data: rows sharded, histograms globally reduced
    (reference: DataParallelTreeLearner)."""

    comm_mode = "data"
    rows_sharded = True


class FeatureParallelTreeLearner(_MeshTreeLearner):
    """tree_learner=feature: data replicated, split search sharded over
    features, winner synced — no data movement, comm is one SplitInfo per
    round (reference: FeatureParallelTreeLearner)."""

    comm_mode = "feature"
    rows_sharded = False


class VotingParallelTreeLearner(_MeshTreeLearner):
    """tree_learner=voting: data-parallel with top-k feature voting to
    bound comm volume as features grow (reference:
    VotingParallelTreeLearner / PV-Tree)."""

    comm_mode = "voting"
    rows_sharded = True


def create_tree_learner(config: Config, dataset: BinnedDataset,
                        mesh: Optional[Mesh] = None) -> SerialTreeLearner:
    """Factory (reference: src/treelearner/tree_learner.cpp:15
    CreateTreeLearner)."""
    kind = config.tree_learner
    if kind == "serial" or mesh is None or mesh.devices.size <= 1:
        return SerialTreeLearner(config, dataset)
    cls = {"data": DataParallelTreeLearner,
           "feature": FeatureParallelTreeLearner,
           "voting": VotingParallelTreeLearner}.get(kind)
    if cls is None:
        Log.fatal("Unknown tree_learner: %s", kind)
    return cls(config, dataset, mesh)
