"""Telemetry: trusted timers, phase tracing, structured run counters.

Codifies PERF.md "measurement discipline v2" as a library instead of a
per-script convention. The facts the primitives encode (each reproduced
multiple times on the v5e/axon terminal, PERF.md rounds 5-7):

- the device profiler MODELS custom-call costs, it does not measure them —
  wall clocks are the only trusted ground truth for Pallas kernels;
- ``block_until_ready`` does not reliably synchronize through the tunnel;
  only a real transfer (``device_get`` / ``np.asarray``) does, so every
  trusted wall must end in :func:`sync`;
- identical re-executions can be deduplicated by the tunnel, so A/B loops
  must thread a CHANGING carry (:func:`ab_interleaved` documents and
  enforces the protocol shape);
- the device clock drifts between runs — only same-process interleaved
  comparisons are trusted.

Three layers:

1. **Trusted timing** — :func:`sync`, :func:`wall`, :func:`timed_sync`,
   :func:`ab_interleaved`. ``bench.py`` and the ``scripts/*_bisect.py`` /
   ``scripts/profile_wall.py`` harnesses build on these.
2. **Phase tracing** — :func:`trace_phase` wraps a region in
   ``jax.named_scope`` + ``jax.profiler.TraceAnnotation`` so profiler
   timelines and HLO dumps carry the learner's phase names (pack,
   histogram, split_scan, partition, score_update, fused dispatch/flush).
   Both are trace/metadata-only: they never change the computed values.
3. **Structured run counters** — the process-global :data:`telemetry`
   registry (counters / gauges / timers / record lists) instrumenting the
   dataset device caches, the fused pipeline, per-tree growth stats and
   every ``auto`` knob resolution. ``Booster.telemetry()``,
   ``CallbackEnv.telemetry``, ``cli --dump-telemetry`` and the bench JSON
   all read :meth:`Telemetry.snapshot`.

All counter updates run on HOST, outside traced code, and never add a
device sync: telemetry keeps bit-parity with an uninstrumented run.
"""
from __future__ import annotations

import contextlib
import re
import threading
import time
from bisect import bisect_left
from collections import defaultdict
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple


# ---------------------------------------------------------------------------
# Trusted timing primitives (PERF.md measurement discipline v2)
# ---------------------------------------------------------------------------

def sync(x) -> Optional[Any]:
    """Force a REAL 1-element device->host transfer dependent on ``x``.

    ``block_until_ready`` can return without the tunnel having executed
    anything (discipline v2 fact 2); an actual transfer cannot. The first
    jax.Array leaf of ``x`` (any pytree) is reduced to one element ON
    DEVICE and ``device_get`` pulled — completing it forces every producer
    of that leaf to have run. Returns the fetched 1-element array, or None
    when ``x`` holds no device arrays (host values need no sync).
    """
    import jax
    for leaf in jax.tree.leaves(x):
        if isinstance(leaf, jax.Array):
            return jax.device_get(leaf.ravel()[:1])
    return None


def monotonic() -> float:
    """Monotonic timestamp (``perf_counter``) for spans that cannot be a
    ``with`` block — e.g. the serve MicroBatcher measures submit->delivery
    latency across threads, so the start and end of the span live in
    different frames. Pure host clock read; callers pair two of these and
    feed the difference to :meth:`Telemetry.add_time`."""
    return time.perf_counter()


class WallTimer:
    """Result handle yielded by :func:`wall`; ``seconds`` is set on exit."""

    __slots__ = ("name", "seconds")

    def __init__(self, name: str) -> None:
        self.name = name
        self.seconds = 0.0


@contextlib.contextmanager
def wall(name: str, record: bool = True) -> Iterator[WallTimer]:
    """Monotonic (``perf_counter``) wall timer around a block.

    Callers timing device work must end the block with ``obs.sync(result)``
    — the timer cannot know what to sync on. The elapsed time lands on the
    yielded handle's ``.seconds`` and (when ``record``) in the global
    telemetry registry under ``wall/<name>``.
    """
    w = WallTimer(name)
    t0 = time.perf_counter()
    try:
        yield w
    finally:
        w.seconds = time.perf_counter() - t0
        if record:
            telemetry.add_time("wall/" + name, w.seconds)


def timed_sync(fn: Callable[[], Any]) -> float:
    """Trusted wall of one call of ``fn``: warm (compile) once, then time a
    second call ended by a forced 1-element transfer of its result."""
    import jax
    r = fn()
    jax.block_until_ready(r)       # warm/compiled; the real sync is below
    t0 = time.perf_counter()
    sync(fn())
    return time.perf_counter() - t0


def ab_interleaved(fns: Sequence[Tuple[str, Callable[[int], Callable[[], Any]]]],
                   reps: int = 5, k: int = 4) -> Dict[str, float]:
    """Interleaved A/B per-op timing under discipline v2.

    ``fns`` is ``[(name, make)]`` where ``make(j)`` returns a zero-arg
    thunk running a j-chained computation (e.g. a ``lax.scan`` of length j)
    whose body threads a CHANGING carry — bit-identical re-executions can
    be deduplicated by the tunnel (fact 3), so the chain must mutate state
    between links. Per-op time = (t_k - t_1) / (k - 1), which cancels the
    dispatch + sync overhead shared by both chain lengths; trials are
    interleaved A, B, A, B per rep (the device clock drifts between runs)
    and the best of ``reps`` is kept. Everything is compiled before the
    first timed trial. Returns ``{name: per_op_seconds}``.
    """
    if k < 2:
        raise ValueError("ab_interleaved needs chain length k >= 2")
    pairs = {name: (make(1), make(k)) for name, make in fns}
    for f1, fk in pairs.values():          # compile everything first
        timed_sync(f1), timed_sync(fk)
    best = {name: float("inf") for name, _ in fns}
    for _ in range(reps):
        for name, (f1, fk) in pairs.items():   # A, B, A, B ... per rep
            best[name] = min(best[name],
                             (timed_sync(fk) - timed_sync(f1)) / (k - 1))
    return best


# ---------------------------------------------------------------------------
# Phase tracing
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def trace_phase(name: str) -> Iterator[None]:
    """Name a hot-phase region for profiler traces, HLO dumps and — when
    span tracing is on — the host-side flight recorder.

    Inside a jit trace, ``jax.named_scope`` stamps the phase name onto the
    emitted HLO ops; on host, ``jax.profiler.TraceAnnotation`` marks the
    span on the profiler timeline. Both are metadata-only — no runtime
    effect on the computed values, so phase-traced trees stay bit-identical
    (tests/test_obs.py rides the existing parity shapes).

    With ``trace_spans=on`` (obs_trace.tracer), host-side executions of
    the region additionally record a span into the flight recorder.
    ``phase_begin`` refuses to record inside a jit trace (that would
    measure trace time once per compile, not runtime) and is a single
    attribute read when tracing is off.
    """
    import jax
    from . import obs_trace
    sp = obs_trace.tracer.phase_begin(name)
    try:
        ann = jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler backend unavailable
        ann = contextlib.nullcontext()
    try:
        with jax.named_scope(name), ann:
            yield
    finally:
        if sp is not None:
            obs_trace.tracer.end(sp)


# ---------------------------------------------------------------------------
# Retrace / compile-budget detection
# ---------------------------------------------------------------------------
#
# Every jit entry point of the training path is wrapped in track_jit(), so
# each (re)trace shows up as a named counter in the telemetry registry:
# ``jit/compiles/<name>``. A retrace explosion (the round-5 "dispatch soup"
# failure class) then reads directly off ``Booster.telemetry()`` /
# ``bench.py`` JSON instead of being inferred from wall-clock, and
# tests/test_retrace.py pins a per-train compile budget.

_JIT_COMPILES_PREFIX = "jit/compiles/"
_BACKEND_COMPILES = "jit/backend_compiles"
_compile_listener_installed = False
# Thread-local mute for the backend-compile listener. obs_device's AOT
# cost capture re-compiles a signature the program ALREADY paid for; its
# backend event would double-count in ``jit/backend_compiles`` (which the
# compile-budget tests pin as "the program's own compiles").
_suppress = threading.local()


@contextlib.contextmanager
def suppress_backend_compiles() -> Iterator[None]:
    """Mute ``jit/backend_compiles`` for compiles issued by the current
    thread inside the block (used by obs_device.on_compile around its AOT
    re-compile). The duration still lands in ``device_cost/capture_s``,
    so the capture cost stays visible — just not conflated with the
    training path's compile count."""
    prev = getattr(_suppress, "on", False)
    _suppress.on = True
    try:
        yield
    finally:
        _suppress.on = prev
# jax.monitoring listeners cannot be unregistered, so the "already
# installed" marker must outlive THIS module object: a reloaded obs (or a
# second copy imported under a different package path) re-running
# install would otherwise stack a second listener and double every
# backend-compile count. The sentinel lives on jax.monitoring itself.
_LISTENER_SENTINEL = "_lightgbm_tpu_compile_listener"


def install_compile_listener() -> None:
    """Count every XLA backend compile into ``jit/backend_compiles``.

    Uses jax.monitoring's duration listener (fires once per
    ``backend_compile`` event, including jits we did not wrap). Idempotent
    across repeated calls, repeated Boosters, and module re-imports (the
    installed marker is a sentinel attribute on ``jax.monitoring``, not
    only a module global — see tests/test_obs.py). A jax without the
    monitoring API degrades to a no-op."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    _compile_listener_installed = True
    try:
        from jax import monitoring
        if getattr(monitoring, _LISTENER_SENTINEL, None) is not None:
            return

        def _on_event(event: str, duration: float, **kw) -> None:
            if "backend_compile" in event:
                if getattr(_suppress, "on", False):
                    return
                telemetry.count(_BACKEND_COMPILES)
                telemetry.add_time("jit/backend_compile_s", duration)

        monitoring.register_event_duration_secs_listener(_on_event)
        setattr(monitoring, _LISTENER_SENTINEL, _on_event)
    except Exception:  # pragma: no cover - older jax without monitoring
        pass


class _TrackedJit:
    """Transparent wrapper over a jitted callable that turns compiled-cache
    growth into telemetry counts.

    ``fn._cache_size()`` (PjitFunction) counts cached executables — one per
    traced signature — so a positive delta across a call means that call
    paid a trace+compile. Attribute access (``.lower()``, ``.trace()``,
    static-argname metadata) delegates to the wrapped function."""

    __slots__ = ("_fn", "_name", "_seen")

    def __init__(self, name: str, fn: Callable[..., Any]) -> None:
        self._fn = fn
        self._name = name
        self._seen = self._size() or 0

    def _size(self) -> Optional[int]:
        try:
            return self._fn._cache_size()
        except Exception:  # pragma: no cover - non-pjit callable
            return None

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        size = self._size()
        if size is not None:
            if size > self._seen:
                telemetry.count(_JIT_COMPILES_PREFIX + self._name,
                                size - self._seen)
                # this exact signature just compiled: hand it to the
                # device-cost capture (AOT cost/memory analysis). Lazy
                # import breaks the obs <-> obs_device cycle; any capture
                # failure is counted there, never raised into training.
                try:
                    from . import obs_device
                    if obs_device.cost_capture_enabled():
                        obs_device.on_compile(self._name, self._fn,
                                              args, kwargs)
                except Exception:  # pragma: no cover - capture is best-effort
                    telemetry.count("device_cost/capture_errors")
            self._seen = size  # shrink = cache cleared; re-arm
        return out

    def __getattr__(self, name: str):
        return getattr(self._fn, name)


def track_jit(name: str, fn: Callable[..., Any]) -> Callable[..., Any]:
    """Wrap a jitted callable so its (re)traces count into
    ``jit/compiles/<name>``. Installs the global backend-compile listener
    on first use. Wrapping an already-tracked callable re-labels it."""
    install_compile_listener()
    if isinstance(fn, _TrackedJit):
        fn = fn._fn
    return _TrackedJit(name, fn)


def jit_compiles() -> Dict[str, int]:
    """Per-entry-point compile counts seen so far (name -> count)."""
    with telemetry._lock:
        return {k[len(_JIT_COMPILES_PREFIX):]: v
                for k, v in telemetry._counters.items()
                if k.startswith(_JIT_COMPILES_PREFIX)}


# ---------------------------------------------------------------------------
# Structured run counters
# ---------------------------------------------------------------------------

def _jsonable(v):
    """Coerce numpy scalars / arrays so snapshot() survives json.dumps."""
    if isinstance(v, (str, bool, int, float)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if hasattr(v, "item"):       # numpy / jax scalar
        try:
            return v.item()
        except Exception:
            pass
    if hasattr(v, "tolist"):
        return v.tolist()
    return repr(v)


def _log_bounds(lo: float = 2.0 ** -10, hi: float = 2.0 ** 20,
                factor: float = 2.0) -> Tuple[float, ...]:
    """Geometric bucket upper bounds lo, lo*f, ..., >= hi."""
    bounds = []
    b = float(lo)
    while b <= hi * (1 + 1e-12):
        bounds.append(b)
        b *= factor
    return tuple(bounds)


# powers of two from ~0.001 to ~1M: one ladder covers latencies in ms
# (10us..17min) and batch sizes in rows (1..1M) at ~2x resolution
DEFAULT_HIST_BOUNDS = _log_bounds()

_PCTS = ((0.50, "p50"), (0.90, "p90"), (0.99, "p99"), (0.999, "p999"))


class Histogram:
    """Log-bucketed histogram: exact counts per geometric bucket, with
    percentiles derived by linear interpolation inside the bucket.

    Replaces the serve latency deque: bounded memory regardless of
    request count, mergeable across processes, and exportable both as
    JSON (``snapshot``) and Prometheus ``_bucket{le=...}`` series
    (:func:`prometheus_text`). NOT internally locked — registry
    instances are guarded by the Telemetry lock; standalone users (the
    MicroBatcher window) bring their own.
    """

    __slots__ = ("bounds", "counts", "count", "sum")

    def __init__(self, bounds: Optional[Sequence[float]] = None) -> None:
        self.bounds = tuple(bounds) if bounds else DEFAULT_HIST_BOUNDS
        self.counts = [0] * (len(self.bounds) + 1)   # last = +Inf overflow
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1   # graftlint: guarded-by=_lock -- caller holds it
        self.sum += v     # graftlint: guarded-by=_lock -- caller holds it
        self.counts[bisect_left(self.bounds, v)] += 1   # le-inclusive

    def percentile(self, q: float) -> float:
        """q in [0, 1]; linear interpolation within the hit bucket
        (Prometheus histogram_quantile semantics)."""
        if self.count == 0:   # graftlint: guarded-by=_lock
            return 0.0
        target = q * self.count   # graftlint: guarded-by=_lock
        cum, lo = 0, 0.0
        for i, hi in enumerate(self.bounds):
            c = self.counts[i]
            if c > 0 and cum + c >= target:
                return lo + (hi - lo) * ((target - cum) / c)
            cum += c
            lo = hi
        return self.bounds[-1]   # overflow bucket: clamp to top bound

    def cumulative(self) -> List[Tuple[Any, int]]:
        """Prometheus-style cumulative buckets: [(le, count<=le), ...,
        ("+Inf", total)]."""
        out = []
        cum = 0
        for i, b in enumerate(self.bounds):
            cum += self.counts[i]
            out.append((b, cum))
        out.append(("+Inf", self.count))   # graftlint: guarded-by=_lock
        return out

    def snapshot(self) -> Dict[str, Any]:
        snap: Dict[str, Any] = {
            "count": self.count,        # graftlint: guarded-by=_lock
            "sum": round(self.sum, 6),  # graftlint: guarded-by=_lock
            "buckets": [[le, c] for le, c in self.cumulative()],
        }
        for q, label in _PCTS:
            snap[label] = round(self.percentile(q), 6)
        return snap


class Telemetry:
    """Process-global registry of counters, gauges, timers and records.

    Thread-safe (the mesh learners and user callbacks may touch it from
    worker threads) and cheap: every mutation is a dict update under one
    lock, on host, never inside traced code. ``snapshot()`` returns a
    plain JSON-serializable dict and folds in ``utils.timer.global_timer``
    so the long-standing phase timers (fused/block_fn, fused/dispatch,
    fused/logs_transfer, ...) appear without double bookkeeping.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, Any] = {}
        self._timers: Dict[str, float] = defaultdict(float)
        self._timer_calls: Dict[str, int] = defaultdict(int)
        self._records: Dict[str, List[dict]] = defaultdict(list)
        self._hists: Dict[str, Histogram] = {}

    # -- mutation --
    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += int(n)

    def gauge(self, name: str, value) -> None:
        with self._lock:
            self._gauges[name] = _jsonable(value)

    def add_time(self, name: str, seconds: float) -> None:
        with self._lock:
            self._timers[name] += float(seconds)
            self._timer_calls[name] += 1

    def observe(self, name: str, value: float,
                bounds: Optional[Sequence[float]] = None) -> None:
        """Add one sample to the log-bucketed histogram ``name``
        (created on first use; ``bounds`` only applies then)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(bounds)
            h.observe(value)

    @contextlib.contextmanager
    def timed(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_time(name, time.perf_counter() - t0)

    @contextlib.contextmanager
    def timed_observe(self, name: str) -> Iterator[None]:
        """Observe the block's wall time in MILLISECONDS into histogram
        ``name`` — for events whose distribution matters (online train
        cycles, promotion swaps), where ``timed`` would collapse them
        into a single running total."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, (time.perf_counter() - t0) * 1e3)

    def record(self, name: str, dedupe_key=None, **payload) -> None:
        """Append a structured event to the ``name`` list. With
        ``dedupe_key``, an event carrying the same key is appended at most
        once (auto-knob resolutions re-run per build_kwargs call but the
        registry keeps one record per distinct resolution)."""
        with self._lock:
            lst = self._records[name]
            if dedupe_key is not None:
                key = _jsonable(dedupe_key)
                if any(r.get("_key") == key for r in lst):
                    return
                payload = dict(payload, _key=key)
            lst.append(_jsonable(payload))

    # -- read --
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def records(self, name: str) -> List[dict]:
        with self._lock:
            return list(self._records.get(name, []))

    def histogram(self, name: str) -> Optional[Dict[str, Any]]:
        """Snapshot of one histogram (buckets + p50/p90/p99/p999), or
        None when nothing was observed under ``name``."""
        with self._lock:
            h = self._hists.get(name)
            return h.snapshot() if h is not None else None

    def snapshot(self, include_global_timer: bool = True) -> Dict[str, Any]:
        """JSON-serializable view of everything recorded so far."""
        with self._lock:
            timers = {k: round(v, 6) for k, v in self._timers.items()}
            calls = dict(self._timer_calls)
            per_fn = {k[len(_JIT_COMPILES_PREFIX):]: v
                      for k, v in self._counters.items()
                      if k.startswith(_JIT_COMPILES_PREFIX)}
            snap = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": timers,
                "timer_calls": calls,
                "jit_compiles": {
                    "per_function": per_fn,
                    "total": sum(per_fn.values()),
                    "backend_compiles":
                        self._counters.get(_BACKEND_COMPILES, 0),
                },
                "records": {k: [dict(r) for r in v]
                            for k, v in self._records.items()},
                "histograms": {k: h.snapshot()
                               for k, h in self._hists.items()},
            }
        if include_global_timer:
            from .utils.timer import global_timer
            for k, v in global_timer.times.items():
                snap["timers"].setdefault(k, round(float(v), 6))
        for lst in snap["records"].values():
            for r in lst:
                r.pop("_key", None)
        try:   # outside self._lock: obs_device has its own lock
            from . import obs_device
            snap["device_cost"] = obs_device.section()
        except Exception:  # pragma: no cover - snapshot must never fail
            snap["device_cost"] = {"enabled": False, "jits": {}, "hbm": {}}
        return snap

    def reset(self) -> None:
        """Clear every counter/gauge/timer/record (tests, fresh benches).
        ``utils.timer.global_timer`` is owned by its callers and is NOT
        reset here."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._timer_calls.clear()
            self._records.clear()
            self._hists.clear()


telemetry = Telemetry()


def safe_metric_part(part: str, max_len: int = 48) -> str:
    """Untrusted id (e.g. an HTTP tenant name) -> safe registry-key
    segment: alnum/dash/underscore only, bounded length, never empty.
    Keeps caller-controlled strings from exploding the flat metric
    namespace or smuggling separators into Prometheus names."""
    s = re.sub(r"[^a-zA-Z0-9_\-]", "_", str(part))[:max_len]
    return s or "_"


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _prom_name(name: str) -> str:
    """Registry key -> legal Prometheus metric name (lgbtpu_ namespace)."""
    return "lgbtpu_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _prom_num(v) -> str:
    if isinstance(v, bool):
        v = int(v)
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def prometheus_text(registry: Optional[Telemetry] = None) -> str:
    """The registry rendered in Prometheus text exposition format
    (version 0.0.4): counters as ``_total``, numeric gauges as gauges,
    timers as ``_seconds_total`` + ``_calls_total`` pairs, histograms as
    cumulative ``_bucket{le="..."}`` / ``_sum`` / ``_count`` series.
    Non-numeric gauges (layout strings, auto-knob records) are skipped —
    they stay on ``/telemetry``. Served by ``GET /metrics`` on
    :class:`serve.http.PredictServer`."""
    reg = telemetry if registry is None else registry
    with reg._lock:
        counters = dict(reg._counters)
        gauges = dict(reg._gauges)
        timers = dict(reg._timers)
        calls = dict(reg._timer_calls)
        hists = {k: h.snapshot() for k, h in reg._hists.items()}
    out: List[str] = []
    seen = set()

    def emit(name: str, typ: str, lines: List[str]) -> List[str]:
        if name in seen:   # sanitization collisions: first family wins
            return []
        seen.add(name)
        return ["# TYPE %s %s" % (name, typ)] + lines

    for k in sorted(counters):
        n = _prom_name(k) + "_total"
        out += emit(n, "counter", ["%s %s" % (n, _prom_num(counters[k]))])
    for k in sorted(gauges):
        v = gauges[k]
        if not isinstance(v, (bool, int, float)):
            continue
        n = _prom_name(k)
        out += emit(n, "gauge", ["%s %s" % (n, _prom_num(v))])
    for k in sorted(timers):
        n = _prom_name(k) + "_seconds_total"
        out += emit(n, "counter", ["%s %s" % (n, _prom_num(timers[k]))])
        c = _prom_name(k) + "_calls_total"
        out += emit(c, "counter", ["%s %s" % (c, _prom_num(calls.get(k, 0)))])
    for k in sorted(hists):
        h = hists[k]
        n = _prom_name(k)
        lines = []
        for le, cum in h["buckets"]:
            le_s = le if isinstance(le, str) else "%g" % le
            lines.append('%s_bucket{le="%s"} %d' % (n, le_s, cum))
        lines.append("%s_sum %s" % (n, _prom_num(h["sum"])))
        lines.append("%s_count %d" % (n, h["count"]))
        out += emit(n, "histogram", lines)
    return "\n".join(out) + "\n"
