"""Host-side span tracing and flight recorder.

The telemetry registry (obs.py) answers "how many / how long in total";
this module answers "where did the wall-clock of THIS request / THIS
training block go".  It provides:

- ``SpanTracer``: nested spans with monotonic start + duration, recorded
  per-thread and optionally carrying a request ``trace_id`` so the serve
  chain (http -> batcher queue/coalesce -> session dispatch -> slice)
  can be stitched back together across threads.
- a bounded **flight recorder**: completed spans land in a ring buffer
  (newest-wins) that can be dumped on demand (``tracer.dump(path)``,
  ``Booster.dump_trace``), at exit (``cli --dump-trace``), or on
  ``SIGUSR2`` (``install_signal_handlers``).
- Chrome trace-event JSON export (``chrome_trace``): load the dump in
  Perfetto (https://ui.perfetto.dev) or chrome://tracing.

Tracing is zero-cost-when-off: the mode flag (``off|on|serve_only``,
config ``trace_spans``) is checked as a plain attribute read before any
allocation, ``span()`` returns a shared no-op context manager, and
``tests/test_trace.py`` pins the off-path overhead compile-budget style.

Spans are HOST-side: inside a jit trace ``phase_begin`` refuses to
record (via ``jax.core.trace_state_clean``), so ``trace_phase`` sites
that live in traced code cost nothing at runtime and do not pollute the
recorder with trace-time measurements.  Device-side attribution stays
with ``jax.named_scope`` / the XLA profiler — but the fused finalize
path splits its spans so device time is visible from host spans alone:
``lgbtpu/fused_device_wait`` (an ``obs.sync`` completion barrier, pure
device-execution wait) precedes ``lgbtpu/fused_flush`` (the actual
result transfer), the host-span mirror of the ``device_s``/
``transfer_s`` bench breakdown (PERF.md, ISSUE 10).

Import-time this module is pure stdlib; jax is resolved lazily when
tracing is first switched on.
"""
import itertools
import json
import os
import threading
import time
from collections import deque

from .obs import telemetry

monotonic = time.perf_counter

DEFAULT_CAPACITY = 65536
MODES = ("off", "on", "serve_only")

# HTTP header carrying a trace id across process boundaries (client ->
# /predict, replica transport -> trainer /fleet endpoints).  The value
# is the decimal trace id; foreign ids (non-numeric) are carried opaque.
TRACE_HEADER = "X-Trace-Id"


def format_trace_id(trace_id):
    """Trace id -> header value."""
    return str(trace_id)


def parse_trace_id(value):
    """Header value -> trace id (int when it parses, else the raw string
    bounded to 128 chars so a hostile header cannot bloat spans), or
    None for absent/blank values."""
    if not value:
        return None
    value = value.strip()
    if not value:
        return None
    try:
        return int(value, 10)
    except ValueError:
        return value[:128]

# histogram family for per-phase timings, fed on every span end while
# tracing is on (per-phase train timings / serve stage timings)
_SPAN_HIST_PREFIX = "span_ms/"


class Span(object):
    """One completed (or in-flight) span. Times are perf_counter floats."""

    __slots__ = ("name", "t0", "dur", "tid", "thread", "trace_id", "args")

    def __init__(self, name, t0, trace_id=None, args=None):
        self.name = name
        self.t0 = t0
        self.dur = 0.0
        self.tid = threading.get_native_id()
        self.thread = threading.current_thread().name
        self.trace_id = trace_id
        self.args = args


class _NullSpan(object):
    """Shared no-op context manager returned when tracing is off.

    A single module-level instance (identity-checkable in tests) so the
    off path allocates nothing per call."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _SpanCtx(object):
    __slots__ = ("_tracer", "span")

    def __init__(self, tracer, span):
        self._tracer = tracer
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, *exc):
        self._tracer.end(self.span)
        return False


def _trace_state_clean_fallback():
    return True


class SpanTracer(object):
    """Thread-aware span tracer with a bounded flight-recorder ring.

    Mode gates which domains record (``train_on`` / ``serve_on`` are
    plain attributes so hot paths pay one attribute read when off):

    - ``off``:        nothing records (default)
    - ``on``:         train phases + serve chain
    - ``serve_only``: only the serve chain (http/batcher/session)
    """

    def __init__(self, capacity=DEFAULT_CAPACITY):
        self.mode = "off"
        self.train_on = False
        self.serve_on = False
        self.spans_started = 0        # monotone; pins off-path overhead
        self._lock = threading.Lock()
        self._ring = deque(maxlen=capacity)
        self._epoch = monotonic()
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._trace_state_clean = _trace_state_clean_fallback
        # fleet process identity: stamped into chrome_trace process_name
        # so merged multi-process exports keep nodes distinguishable
        self.identity_role = None
        self.identity_holder = None

    # ------------------------------------------------------------- setup
    def configure(self, mode, capacity=None):
        """Set the tracing mode (and optionally resize the ring)."""
        if mode not in MODES:
            raise ValueError("trace_spans must be one of %s, got %r"
                             % ("|".join(MODES), mode))
        if capacity is not None and capacity != self._ring.maxlen:
            with self._lock:
                self._ring = deque(self._ring, maxlen=max(1, int(capacity)))
        self.mode = mode
        self.train_on = mode == "on"
        self.serve_on = mode in ("on", "serve_only")
        if self.serve_on or self.train_on:
            # host spans must not record while jax is tracing a function:
            # that would measure trace time once per compile, not runtime.
            try:
                from jax.core import trace_state_clean
                self._trace_state_clean = trace_state_clean
            except Exception:
                self._trace_state_clean = _trace_state_clean_fallback
        return self

    def new_trace_id(self):
        # pid-salted so ids minted by different fleet processes never
        # collide when their traces are merged into one Perfetto load;
        # getpid() is read per call so forked children stay distinct
        return ((os.getpid() & 0x3FFFFF) << 40) | next(self._ids)

    def set_identity(self, role=None, holder=None):
        """Label this process for multi-process trace merges (fleet
        role + holder id; cli serve sets this when fleet mode is on)."""
        with self._lock:
            self.identity_role = role
            self.identity_holder = holder

    def identity(self):
        """JSON-serializable process identity (pid always present)."""
        with self._lock:
            role, holder = self.identity_role, self.identity_holder
        doc = {"pid": os.getpid()}
        if role:
            doc["role"] = role
        if holder:
            doc["holder"] = holder
        return doc

    def current_trace_id(self):
        """Trace id of the innermost open span on this thread (None when
        no span is open) — lets the fleet transport propagate the active
        request's id over HTTP without threading it through every call."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1].trace_id if stack else None

    # ----------------------------------------------------------- spanning
    def _stack(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def begin(self, name, trace_id=None, args=None):
        """Open a span on the current thread; returns it for end()."""
        stack = self._stack()
        if trace_id is None and stack:
            trace_id = stack[-1].trace_id
        sp = Span(name, monotonic(), trace_id, args)
        stack.append(sp)
        with self._lock:
            self.spans_started += 1
        return sp

    def end(self, sp):
        """Close a span: fix duration, pop the stack, hit the recorder."""
        sp.dur = monotonic() - sp.t0
        stack = self._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        elif sp in stack:            # tolerate out-of-order ends
            stack.remove(sp)
        with self._lock:
            self._ring.append(sp)
        telemetry.observe(_SPAN_HIST_PREFIX + sp.name, sp.dur * 1e3)

    def span(self, name, domain="train", trace_id=None, **args):
        """Context-manager span; shared no-op when the domain is off.
        The ``online`` domain (continual-refit trainer: train cycles,
        shadow scoring, promotion swaps) records whenever the serve chain
        does — promotions are part of the serving story, and serve_only
        deployments must still see them."""
        on = self.serve_on if domain in ("serve", "online") else self.train_on
        if not on:
            return NULL_SPAN
        return _SpanCtx(self, self.begin(name, trace_id, args or None))

    def phase_begin(self, name):
        """Hot-path hook for obs.trace_phase: no kwargs, no allocation
        when train tracing is off or a jit trace is in flight."""
        if not self.train_on:
            return None
        if not self._trace_state_clean():
            return None
        return self.begin(name)

    def record(self, name, t0, t1, trace_id=None, args=None):
        """Record a retroactive span from explicit timestamps (e.g. the
        batcher marking a request's queue wait after dequeue)."""
        sp = Span(name, t0, trace_id, args)
        sp.dur = max(0.0, t1 - t0)
        with self._lock:
            self.spans_started += 1
            self._ring.append(sp)
        telemetry.observe(_SPAN_HIST_PREFIX + name, sp.dur * 1e3)
        return sp

    # ------------------------------------------------------------- export
    def events(self):
        """Completed spans currently in the flight recorder (oldest
        first; bounded by the ring capacity)."""
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._epoch = monotonic()

    def chrome_trace(self):
        """Flight recorder as a Chrome trace-event JSON object
        (Perfetto / chrome://tracing loadable)."""
        with self._lock:
            spans = list(self._ring)
            epoch = self._epoch
            id_role, id_holder = self.identity_role, self.identity_holder
        pid = os.getpid()
        threads = {}
        events = []
        for sp in spans:
            threads.setdefault(sp.tid, sp.thread)
            ev = {"name": sp.name, "ph": "X", "pid": pid, "tid": sp.tid,
                  "ts": round((sp.t0 - epoch) * 1e6, 3),
                  "dur": round(sp.dur * 1e6, 3)}
            args = dict(sp.args) if sp.args else {}
            if sp.trace_id is not None:
                args["trace_id"] = sp.trace_id
            if args:
                ev["args"] = args
            events.append(ev)
        pname = "lightgbm-tpu"
        if id_role or id_holder:
            pname += " [%s]" % " ".join(
                str(x) for x in (id_role, id_holder) if x)
        meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "args": {"name": pname}}]
        for tid in sorted(threads):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": threads[tid]}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def dump(self, path):
        """Write the Chrome trace JSON atomically; returns event count."""
        doc = self.chrome_trace()
        _atomic_write_json(path, doc)
        return len(doc["traceEvents"])


tracer = SpanTracer()


def _atomic_write_json(path, obj):
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)


# --------------------------------------------------------------- dumping
def dump_telemetry(path):
    """Write the telemetry registry snapshot (atomic replace, so a
    reader never sees a torn file even mid-dump)."""
    _atomic_write_json(path, telemetry.snapshot())


def install_signal_handlers(telemetry_path=None, trace_path=None):
    """SIGUSR1 -> telemetry snapshot, SIGUSR2 -> trace dump.

    Lets a hung/live server be inspected from outside:
    ``kill -USR1 <pid>``.  Main-thread only (signal module constraint);
    silently a no-op on platforms without SIGUSR1/2. Returns the list of
    signals installed."""
    import signal
    installed = []
    if telemetry_path and hasattr(signal, "SIGUSR1"):
        def _usr1(signum, frame):
            dump_telemetry(telemetry_path)
        signal.signal(signal.SIGUSR1, _usr1)
        installed.append("SIGUSR1")
    if trace_path and hasattr(signal, "SIGUSR2"):
        def _usr2(signum, frame):
            tracer.dump(trace_path)
        signal.signal(signal.SIGUSR2, _usr2)
        installed.append("SIGUSR2")
    return installed


def start_periodic_telemetry_dump(path, interval_s):
    """Dump telemetry to `path` every `interval_s` seconds from a named
    daemon thread until the returned Event is set (cli serve uses this
    so a wedged server still leaves fresh counters on disk)."""
    stop = threading.Event()

    def _loop():
        while not stop.wait(interval_s):
            try:
                dump_telemetry(path)
            except OSError:
                pass

    t = threading.Thread(target=_loop, name="lgbtpu-telemetry-dump",
                         daemon=True)
    t.start()
    return stop
