"""Forest-at-once ensemble inference: one Pallas launch per row tile.

The serving predict path (``ops/predict.py predict_raw_impl``) walks the
packed ensemble as a ``fori_loop`` of per-split elementwise passes per
tree group — dozens of small launches per bucket dispatch, each reading
the full (N, F) raw matrix from HBM. This module reshapes the MODEL for
inference instead (the accelerator-GBDT literature's move: arXiv
1706.08359, arXiv 2011.02022):

- :class:`ForestPack` is an inference-shaped repack of ``PackedSplits``:
  node tables are SPLIT-MAJOR ``(R rounds, T trees)`` so round ``r``
  streams one contiguous row of every per-split quantity, and thresholds
  live in BIN space (derived through the same per-split conversion
  ``tree_to_bin_log`` uses — see ``split_bin_table`` in ops/predict.py),
  so every comparison is a small-int compare instead of an f32 one.
- :func:`forest_predict_impl` evaluates the WHOLE ensemble for a row
  tile in ONE ``pl.pallas_call``: the (tile, T) traversal front lives in
  VMEM/registers, each routing round gathers the per-tree feature column
  with a one-hot MXU contraction (``bins_f32 @ onehot(feature_r)`` — the
  ``leaf_values_by_row`` gather-to-matmul trick), and leaf values are
  accumulated in-kernel in the ORACLE'S exact grouping/order so the
  result is byte-identical to ``predict_raw_impl``.

Bit-parity discipline (PR 12): the per-depth-gather path stays the
serving default and the oracle; this kernel is behind the
``tpu_forest_kernel`` knob, proven byte-identical under the pallas
interpreter (tests/test_forest_kernel.py), and ``auto`` resolves to
``off`` until ``scripts/forest_bisect.py`` validates the Mosaic lowering
and a wall win on real hardware.
"""
from __future__ import annotations

from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is optional at import time (CPU meshes use the XLA path)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    if not hasattr(pltpu, "HBM"):  # older jax spells these differently
        pltpu.HBM = pltpu.ANY
        pltpu.CompilerParams = pltpu.TPUCompilerParams
except Exception:  # pragma: no cover
    pl = pltpu = None

#: Row-tile width of one kernel program. Bucket rungs need not be
#: multiples of it — the wrapper pads (padding rows route harmlessly and
#: are sliced off).
FOREST_TILE = 256

#: VMEM budget for the resident node tables + per-tile working set; a
#: model whose tables exceed it is ineligible (the front + tables must
#: stay resident for the one-launch traversal to make sense).
FOREST_VMEM_BUDGET = 8 << 20

_HIGH = jax.lax.Precision.HIGHEST


class ForestPack(NamedTuple):
    """Inference-shaped ensemble tables, BIN space, split-major.

    (R routing rounds, T trees — padded to the tree_batch multiple, L
    leaf slots, Kc max left-routing category bins, Km max linear leaf
    features). ``default_left``/``movable`` ride as i32 0/1 and
    ``coeff_mask`` as f32 0/1: Mosaic cannot truncate i8/i1 vectors, and
    the f32 mask feeds the oracle-mirroring ``> 0.5`` compare.
    """
    slot: jax.Array          # (R, T) i32 leaf slot split in round r
    feature: jax.Array       # (R, T) i32 INNER feature index (bin matrix)
    tbin: jax.Array          # (R, T) i32 threshold bin (go left: b <= tbin)
    kind: jax.Array          # (R, T) i32 0 numerical / 1 categorical
    default_left: jax.Array  # (R, T) i32 0/1
    miss_bin: jax.Array      # (R, T) i32 movable-missing bin
    movable: jax.Array       # (R, T) i32 0/1 miss_bin overrides the compare
    num_splits: jax.Array    # (T,) i32
    value_of_slot: jax.Array  # (T, L) f32 leaf outputs by slot
    tree_class: jax.Array    # (T,) i32
    cat_bins: jax.Array      # (R, T, Kc) i32 bins routed LEFT, pad -2
    # linear-leaf tables (RAW-space: evaluated against the raw row tile,
    # exactly like linear_values_by_row in the oracle)
    const_of_slot: jax.Array  # (T, L) f32
    coeff: jax.Array          # (T, L, Km) f32
    coeff_feat: jax.Array     # (T, L, Km) i32 inner feature index
    coeff_mask: jax.Array     # (T, L, Km) f32 0/1


def forest_table_bytes(fp: ForestPack) -> int:
    """Device bytes of the resident node tables (the eligibility bound)."""
    return int(sum(np.prod(a.shape) * a.dtype.itemsize for a in fp))


def forest_pack(trees: List, dataset, num_class: int = 1,
                tree_batch: int = 8) -> Tuple[ForestPack, bool, bool]:
    """Pack host trees into BIN-space split-major device tables.

    ``dataset`` supplies the bin mappers (the booster's constructed
    train_set). Raises ``ValueError`` when a split's feature has no inner
    index in the dataset (loaded models splitting on features the
    mappers never saw cannot route in BIN space — the raw oracle path
    serves those). Returns ``(pack, has_cat, has_linear)``.
    """
    from .predict import split_bin_table

    T = max(len(trees), 1)
    pad_t = (-T) % tree_batch
    Tp = T + pad_t
    arrs = [t.to_split_arrays() for t in trees]
    tables = []
    for t, a in zip(trees, arrs):
        tbl = split_bin_table(a, dataset)
        if not bool(tbl["valid"].all()):
            raise ValueError(
                "forest pack: split feature(s) absent from the dataset's "
                "bin mappers (loaded model?) — BIN-space routing undefined")
        tables.append(tbl)
    R = max((len(a["slot"]) for a in arrs), default=0)
    R = max(R, 1)
    L = R + 1
    Kc = max((len(c) for tbl in tables for c in tbl["cat_bins"].values()),
             default=0)
    has_cat = Kc > 0
    Kc = max(Kc, 1)

    slot = np.zeros((Tp, R), np.int32)
    feature = np.zeros((Tp, R), np.int32)
    tbin = np.zeros((Tp, R), np.int32)
    kind = np.zeros((Tp, R), np.int32)
    default_left = np.zeros((Tp, R), np.int32)
    miss_bin = np.zeros((Tp, R), np.int32)
    movable = np.zeros((Tp, R), np.int32)
    num_splits = np.zeros(Tp, np.int32)
    value_of_slot = np.zeros((Tp, L), np.float32)
    tree_class = np.zeros(Tp, np.int32)
    cat_bins = np.full((Tp, R, Kc), -2, np.int64)
    for ti, (t, a, tbl) in enumerate(zip(trees, arrs, tables)):
        r = len(a["slot"])
        num_splits[ti] = r
        tree_class[ti] = ti % num_class
        slot[ti, :r] = a["slot"]
        feature[ti, :r] = tbl["feature"][:r]
        tbin[ti, :r] = tbl["tbin"][:r]
        kind[ti, :r] = a["kind"]
        default_left[ti, :r] = a["default_left"]
        miss_bin[ti, :r] = tbl["miss_bin"][:r]
        movable[ti, :r] = tbl["movable"][:r]
        lv = t.leaf_value[a["leaf_of_slot"][:r + 1]] if t.num_leaves > 1 \
            else t.leaf_value[:1]
        value_of_slot[ti, :len(lv)] = lv
        for rr, bins_left in tbl["cat_bins"].items():
            cat_bins[ti, rr, :len(bins_left)] = bins_left
    from ..linear.pack import linear_pack_arrays
    const_of_slot, coeff, coeff_feat, coeff_mask, has_linear = \
        linear_pack_arrays(trees, arrs, value_of_slot[:T])
    # linear tables come back (T, L, Km); pad trees and remap coeff
    # features to INNER indices (the kernel gathers from the raw tile in
    # inner-feature column order)
    Km = coeff.shape[2]
    cfeat_inner = np.zeros((Tp, L, Km), np.int32)
    if has_linear:
        inner_of = np.array(
            [dataset.inner_feature_index(j)
             for j in range(int(dataset.num_total_features))], np.int64)
        cf = np.asarray(coeff_feat, np.int64)
        mapped = inner_of[np.clip(cf, 0, len(inner_of) - 1)]
        if bool(((mapped < 0) & np.asarray(coeff_mask, bool)).any()):
            raise ValueError(
                "forest pack: linear-leaf feature absent from the "
                "dataset's bin mappers — raw gather column undefined")
        cfeat_inner[:T] = np.where(np.asarray(coeff_mask, bool),
                                   np.clip(mapped, 0, None), 0)

    def _pad(a):
        out = np.zeros((Tp,) + a.shape[1:], a.dtype)
        out[:T] = a
        return out

    fp = ForestPack(
        slot=jnp.asarray(slot.T, jnp.int32),
        feature=jnp.asarray(feature.T, jnp.int32),
        tbin=jnp.asarray(tbin.T, jnp.int32),
        kind=jnp.asarray(kind.T, jnp.int32),
        default_left=jnp.asarray(default_left.T, jnp.int32),
        miss_bin=jnp.asarray(miss_bin.T, jnp.int32),
        movable=jnp.asarray(movable.T, jnp.int32),
        num_splits=jnp.asarray(num_splits, jnp.int32),
        value_of_slot=jnp.asarray(value_of_slot, jnp.float32),
        tree_class=jnp.asarray(tree_class, jnp.int32),
        cat_bins=jnp.asarray(np.transpose(cat_bins, (1, 0, 2)), jnp.int32),
        const_of_slot=jnp.asarray(_pad(np.asarray(const_of_slot)),
                                  jnp.float32),
        coeff=jnp.asarray(_pad(np.asarray(coeff)), jnp.float32),
        coeff_feat=jnp.asarray(cfeat_inner, jnp.int32),
        coeff_mask=jnp.asarray(
            _pad(np.asarray(coeff_mask, np.float32)), jnp.float32))
    return fp, has_cat, bool(has_linear)


def _dot(a: jax.Array, b: jax.Array) -> jax.Array:
    # 0/1 f32 contractions select exactly at HIGHEST (leaf_values_by_row)
    return jax.lax.dot(a, b, precision=_HIGH,
                       preferred_element_type=jnp.float32)


def _halving_sum(rows: List[jax.Array]) -> jax.Array:
    """f32 sum of a static list in XLA's reduce association.

    ``jnp.sum`` written INSIDE the interpreted kernel body lowers to a
    sequential chain, but the oracle's reductions compile to XLA's
    recursive halving over the next power of two with implicit zeros —
    ``((v0+v4)+(v2+v6)) + ((v1+v5)+(v3+v7))`` for 8 terms. Spelling that
    association out (zero pads included, so ``-0.0`` partials flush to
    ``+0.0`` exactly like XLA's) is what makes the kernel's f32 adds land
    bit-identically to ``predict_raw_impl``'s."""
    n = 1
    while n < len(rows):
        n *= 2
    rows = list(rows) + [jnp.zeros_like(rows[0])] * (n - len(rows))
    while len(rows) > 1:
        half = len(rows) // 2
        rows = [rows[i] + rows[i + half] for i in range(half)]
    return rows[0]


def _linear_leaf_values(X, oh, val_t, const_t, coeff_t, cfeat_t, cmask_t):
    """Per-row linear-leaf outputs for one tree, mirroring
    ``linear_values_by_row`` op-for-op (selections are exact, the km
    contraction runs in the oracle's index order) — except the raw-value
    gather, which becomes a NaN-split one-hot contraction: Mosaic has no
    ``take_along_axis``, and gathering value and NaN-mask separately
    keeps the selected bits identical."""
    f32 = jnp.float32
    base = _dot(oh, val_t[:, None])[:, 0]
    cst = _dot(oh, const_t[:, None])[:, 0]
    cf = _dot(oh, coeff_t)                                   # (tile, km)
    fi = _dot(oh, cfeat_t.astype(f32)).astype(jnp.int32)     # (tile, km)
    cm = _dot(oh, cmask_t) > f32(0.5)
    xnan = jnp.isnan(X)
    xz = jnp.where(xnan, f32(0), X)
    xnan_f = xnan.astype(f32)
    km = coeff_t.shape[1]
    zs, nans = [], []
    fiota = jax.lax.broadcasted_iota(jnp.int32, X.shape, 1)
    for k in range(km):
        ohf = (fi[:, k][:, None] == fiota).astype(f32)       # (tile, F)
        # batched 1xF @ Fx1 dot, not an elementwise mask-and-sum: a dot
        # MATERIALIZES, so the gathered value is rounded on its own
        # instead of fusing into the km contraction below (fused, the
        # compiler reassociates across both reduces and the low bit
        # diverges from the oracle's take_along_axis + sum)
        zs.append(jax.lax.dot_general(
            xz[:, None, :], ohf[:, :, None],
            (((2,), (1,)), ((0,), (0,))), precision=_HIGH,
            preferred_element_type=f32)[:, 0, 0])
        nans.append(jnp.sum(xnan_f * ohf, axis=1) > f32(0.5))
    z = jnp.stack(zs, axis=1)                                # (tile, km)
    nan = jnp.stack(nans, axis=1)
    nanrow = jnp.any(nan & cm, axis=1)
    zz = jnp.where(cm & jnp.logical_not(nan), z, f32(0))
    # the oracle's exact expression: an axis-1 mul+reduce lowers to the
    # same halving reduction here as in predict_raw_impl's program (the
    # axis-0 TREE sum does not — see _halving_sum)
    contrib = jnp.sum(zz * cf, axis=1)
    return jnp.where(nanrow, base, cst + contrib)


def forest_predict_impl(bins: jax.Array, X: jax.Array, fp: ForestPack, *,
                        num_class: int = 1, has_cat: bool = False,
                        has_linear: bool = False, tree_batch: int = 8,
                        tile: int = FOREST_TILE,
                        interpret=None) -> jax.Array:
    """(N, F) inner-feature bins (+ raw rows for linear leaves) -> raw
    ensemble scores, byte-identical to ``predict_raw_impl``.

    One kernel program per row tile; all node tables resident. ``X`` is
    only an operand when ``has_linear`` (it is ignored — and never
    shipped into VMEM — otherwise). N is padded up to the tile multiple
    and sliced back.
    """
    if pl is None:  # pragma: no cover - pallas always importable in CI
        raise RuntimeError("pallas unavailable: forest kernel cannot run")
    n, F = bins.shape
    R, T = fp.slot.shape
    L = fp.value_of_slot.shape[1]
    K = max(1, int(num_class))
    assert T % tree_batch == 0, (T, tree_batch)
    pad = (-n) % tile
    if pad:
        bins = jnp.concatenate(
            [bins, jnp.zeros((pad, F), bins.dtype)], axis=0)
        if has_linear:
            X = jnp.concatenate(
                [X, jnp.zeros((pad, F), jnp.float32)], axis=0)
    npad = n + pad
    grid = npad // tile
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def kernel(*refs):
        out_ref = refs[-1]
        it = iter(refs[:-1])
        binsf = next(it)[...].astype(jnp.float32)            # (tile, F)
        xraw = next(it)[...] if has_linear else None         # (tile, F)
        slot_t = next(it)[...]                               # (R, T)
        feat_t = next(it)[...]
        tbin_t = next(it)[...]
        kind_t = next(it)[...]
        dl_t = next(it)[...]
        miss_t = next(it)[...]
        mov_t = next(it)[...]
        ns = next(it)[...]                                   # (T,)
        val = next(it)[...]                                  # (T, L)
        cls = next(it)[...]                                  # (T,)
        cat = next(it)[...] if has_cat else None             # (R, T, Kc)
        if has_linear:
            const = next(it)[...]
            coeff = next(it)[...]
            cfeat = next(it)[...]
            cmask = next(it)[...]
        fiota = jax.lax.broadcasted_iota(jnp.int32, (F, T), 0)

        def step(r, front):
            idx = lambda tab: jax.lax.dynamic_index_in_dim(  # noqa: E731
                tab, r, 0, keepdims=False)
            srow, frow, trow = idx(slot_t), idx(feat_t), idx(tbin_t)
            krow, dlrow = idx(kind_t), idx(dl_t)
            mrow, movrow = idx(miss_t), idx(mov_t)
            # gather-to-matmul: per-tree feature column for this round
            oh = (fiota == frow[None, :]).astype(jnp.float32)
            colb = _dot(binsf, oh).astype(jnp.int32)         # (tile, T)
            go = colb <= trow[None, :]
            go = jnp.where((movrow[None, :] == 1) & (colb == mrow[None, :]),
                           dlrow[None, :] == 1, go)
            if has_cat:
                crow = jax.lax.dynamic_index_in_dim(cat, r, 0,
                                                    keepdims=False)
                in_set = jnp.any(colb[:, :, None] == crow[None, :, :],
                                 axis=-1)
                go = jnp.where(krow[None, :] > 0, in_set, go)
            upd = jnp.where((front == srow[None, :]) & ~go, r + 1, front)
            return jnp.where(r < ns[None, :], upd, front)

        front = jax.lax.fori_loop(
            0, R, step, jnp.zeros((tile, T), jnp.int32))     # (tile, T)

        # leaf accumulation mirrors the oracle: static loop over
        # tree_batch groups, per-group sums in XLA's halving association
        # (_halving_sum above), group partials chained in the order the
        # oracle's scan carries them

        liota = jax.lax.broadcasted_iota(jnp.int32, (tile, L), 1)
        if K > 1:
            score = jnp.zeros((tile, K), jnp.float32)
            kiota = jnp.arange(K, dtype=jnp.int32)
        else:
            score = jnp.zeros((tile,), jnp.float32)
        for g in range(T // tree_batch):
            vals_rows = []
            for j in range(tree_batch):
                t = g * tree_batch + j
                oh = (front[:, t][:, None] == liota).astype(jnp.float32)
                if has_linear:
                    v = _linear_leaf_values(xraw, oh, val[t], const[t],
                                            coeff[t], cfeat[t], cmask[t])
                else:
                    v = _dot(oh, val[t][:, None])[:, 0]
                vals_rows.append(v)
            if K > 1:
                cls_g = cls[g * tree_batch:(g + 1) * tree_batch]
                cls_oh = (cls_g[:, None] == kiota[None, :]).astype(
                    jnp.float32)
                vals = jnp.stack(vals_rows, axis=0)          # (tb, tile)
                score = score + vals.T @ cls_oh
            else:
                score = score + _halving_sum(vals_rows)
        out_ref[...] = score[:, None] if K == 1 else score

    def _whole(a):
        nd = a.ndim
        return pl.BlockSpec(a.shape, lambda i, _n=nd: (0,) * _n)

    operands = [bins]
    in_specs = [pl.BlockSpec((tile, F), lambda i: (i, 0))]
    if has_linear:
        operands.append(X.astype(jnp.float32))
        in_specs.append(pl.BlockSpec((tile, F), lambda i: (i, 0)))
    tables = [fp.slot, fp.feature, fp.tbin, fp.kind, fp.default_left,
              fp.miss_bin, fp.movable, fp.num_splits, fp.value_of_slot,
              fp.tree_class]
    if has_cat:
        tables.append(fp.cat_bins)
    if has_linear:
        tables += [fp.const_of_slot, fp.coeff, fp.coeff_feat,
                   fp.coeff_mask]
    operands += tables
    in_specs += [_whole(a) for a in tables]
    kwargs = {}
    if not interpret:  # pragma: no cover - needs real TPU
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    out = pl.pallas_call(
        kernel,
        name="forest_predict",
        grid=(grid,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((tile, K), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((npad, K), jnp.float32),
        interpret=interpret,
        **kwargs,
    )(*operands)
    out = out[:n]
    return out[:, 0] if num_class <= 1 else out
