"""Pallas TPU histogram kernel.

The make-or-break op (SURVEY.md §7 "Scatter-add histogram throughput on
TPU"; reference hot loop: src/io/dense_bin.hpp:98 ConstructHistogramInner and
the GPU kernels src/treelearner/ocl/histogram256.cl).

The XLA fallback (ops/histogram.py) materializes the (chunk, F*B) one-hot in
HBM — ~B bytes of traffic per (row, feature) cell. This kernel builds the
one-hot tile in VMEM only, leaving HBM traffic at the information-theoretic
floor: one int8 read per (row, feature) cell per bin-block, plus the
(g,h,cnt) channels. The per-leaf row mask is computed in-kernel from
``row_leaf`` so no masked copy of the gradient channels is ever written.

Tiling: grid (bin_blocks, row_chunks). Each step loads a (C, F) slab of the
binned matrix and accumulates the one-hot x channels matmul for a BB-wide
range of bins; row chunks iterate innermost, revisiting (and accumulating
into) the same output block. One-hot lanes use pltpu.repeat's tile layout:
lane l -> (bin = l // F, feature = l % F). All comparisons run in bfloat16
(bin ids <= 255 are exact) and the f32 channels are split hi+lo bf16 so two
MXU passes reproduce f32 accuracy.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C_PAD = 8                      # channel count padded to the f32 sublane tile
LANE_TARGET = 2048             # one-hot lanes per grid step
VMEM_BUDGET = 6 * 1024 * 1024  # bytes for the in-flight one-hot working set
MAX_PALLAS_BINS = 256          # bf16 integer-exactness bound


def _kernel(leaf_ref, bins_ref, ghc_ref, row_leaf_ref, lane_bin_ref, out_ref,
            *, bb, fg):
    i = pl.program_id(1)       # row chunk

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins_blk = bins_ref[:]                         # (C, F) int8/16
    ghc_blk = ghc_ref[:]                           # (C, C_PAD) f32
    leaf = leaf_ref[0]
    mask = jnp.logical_or(leaf < 0, row_leaf_ref[:] == leaf)   # (C, 1)
    ghcm = ghc_blk * mask.astype(jnp.float32)

    # arithmetic one-hot, all bfloat16 (integers <= 256 exact): for integer
    # d = bin - lane_bin, relu(1 - d^2) is exactly the indicator d == 0.
    # Avoids int32 tiles and vector compares the target cannot lower.
    rep = pltpu.repeat(bins_blk.astype(jnp.int32).astype(jnp.bfloat16),
                       bb, axis=1)                 # (C, bb*F)
    d = rep - lane_bin_ref[0, 0:1, :]              # (C, bb*F) - (1, bb*F)
    oh = jnp.maximum(jnp.bfloat16(1.0) - d * d, jnp.bfloat16(0.0))

    hi = ghcm.astype(jnp.bfloat16)
    lo = (ghcm - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    acc = jax.lax.dot(hi.T, oh, preferred_element_type=jnp.float32)
    acc = acc + jax.lax.dot(lo.T, oh, preferred_element_type=jnp.float32)
    out_ref[:] += acc                               # (C_PAD, bb*F)


@functools.partial(jax.jit, static_argnames=("num_bins",))
def hist_pallas(bins, ghc, row_leaf, leaf, num_bins: int):
    """(N, F) int bins + (N, C) f32 channels + (N,) row_leaf + scalar leaf
    -> (F, num_bins, C) f32 histogram of rows on ``leaf`` (all rows when
    leaf < 0)."""
    n, num_feat = bins.shape
    c = ghc.shape[1]
    # lane count bb*f_pad must be 128-divisible: pad features to a multiple
    # of 32 and use bin-blocks in multiples of 4
    f_pad_to = ((num_feat + 31) // 32) * 32
    bb = max(4, (min(num_bins + 3, LANE_TARGET // f_pad_to) // 4) * 4)
    b_pad = ((num_bins + bb - 1) // bb) * bb
    n_bb = b_pad // bb
    lanes = bb * f_pad_to
    # ~5 bytes per (row, lane) cell: bf16 repeat tile + bf16 one-hot + slack
    row_chunk = max(8, min(1024, (VMEM_BUDGET // (lanes * 5)) // 8 * 8))
    r_pad = (-n) % row_chunk
    if f_pad_to != num_feat:
        bins = jnp.pad(bins, ((0, 0), (0, f_pad_to - num_feat)))

    row_leaf2d = row_leaf.astype(jnp.int32).reshape(-1, 1)
    if r_pad:
        bins = jnp.pad(bins, ((0, r_pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, r_pad), (0, 0)))
        # padded rows: never match any leaf; zero channels cover the root pass
        row_leaf2d = jnp.pad(row_leaf2d, ((0, r_pad), (0, 0)),
                             constant_values=-2)
    if c < C_PAD:
        ghc = jnp.pad(ghc, ((0, 0), (0, C_PAD - c)))
    n_pad = bins.shape[0]
    n_rc = n_pad // row_chunk
    leaf_arr = jnp.asarray([leaf], jnp.int32)
    # precomputed lane -> bin id table, bf16; sublane dim padded to 8 to
    # satisfy block-shape tiling
    lb = (np.arange(b_pad * f_pad_to) // f_pad_to).reshape(n_bb, 1, lanes)
    lane_bin = jnp.asarray(np.broadcast_to(lb, (n_bb, 8, lanes))
                           .astype(np.float32), jnp.bfloat16)

    out = pl.pallas_call(
        functools.partial(_kernel, bb=bb, fg=f_pad_to),
        grid=(n_bb, n_rc),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((row_chunk, f_pad_to), lambda j, i: (i, 0)),
            pl.BlockSpec((row_chunk, C_PAD), lambda j, i: (i, 0)),
            pl.BlockSpec((row_chunk, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((1, 8, lanes), lambda j, i: (j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((C_PAD, bb * f_pad_to), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((C_PAD, b_pad * f_pad_to), jnp.float32),
    )(leaf_arr, bins, ghc, row_leaf2d, lane_bin)

    # undo lane layout: blocks of bb bins, each lane = local_bin * F + feat
    hist = out[:c].reshape(c, n_bb * bb, f_pad_to)   # (C, bin, feat)
    hist = hist.transpose(2, 1, 0)                   # (feat, bin, C)
    return hist[:num_feat, :num_bins, :]


def pallas_available(num_bins: int) -> bool:
    if num_bins > MAX_PALLAS_BINS:
        return False
    try:
        dev = jax.devices()[0]
    except Exception:  # pragma: no cover
        return False
    return dev.platform in ("tpu", "axon") or "TPU" in str(dev)
