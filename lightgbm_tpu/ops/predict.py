"""Batched on-device prediction over packed tree arrays.

TPU-native replacement for the reference's per-row pointer walk
(reference: include/LightGBM/tree.h:133 Tree::Predict,
src/boosting/gbdt_prediction.cpp, src/application/predictor.hpp:29).

Design: every tree flattens into leaf-slot split order
(Tree.to_split_arrays — the learner's TreeLog convention), and rows are
routed ARITHMETICALLY: split r tests raw values against its threshold and
moves non-left rows from slot[r] to slot r+1. No per-row pointer chasing,
no table gathers (TPU element gathers are ~60ns/row); every step is a
bandwidth-bound elementwise op over all rows, batched over trees with vmap.
Missing handling mirrors tree.h NumericalDecision: NaN follows the default
direction for MissingType::NaN, otherwise becomes 0; zeros follow the
default direction for MissingType::Zero. Categorical splits test set
membership against padded category tables.

Routing works on RAW feature values, so it serves trained boosters and
models loaded from reference-format text identically (no bin mappers
needed).
"""
from __future__ import annotations

from typing import List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import track_jit

K_ZERO = 1e-35


class PackedSplits(NamedTuple):
    """(T trees, R max splits, L max leaves, Kc max categories, Km max
    linear leaf features)"""
    slot: jax.Array          # (T, R) i32
    feature: jax.Array       # (T, R) i32 column index into X
    threshold: jax.Array     # (T, R) f32
    kind: jax.Array          # (T, R) i32  0 numerical / 1 categorical
    default_left: jax.Array  # (T, R) bool
    missing_type: jax.Array  # (T, R) i32
    num_splits: jax.Array    # (T,) i32
    value_of_slot: jax.Array  # (T, L) f32 leaf outputs by slot
    tree_class: jax.Array    # (T,) i32
    cat_values: jax.Array    # (T, R, Kc) i32, padded with -2 (never matches)
    # linear-leaf tables (lightgbm_tpu/linear/pack.py): non-linear trees
    # carry const == value and an all-false mask, which evaluates to the
    # plain leaf output — one program shape serves mixed ensembles
    const_of_slot: jax.Array  # (T, L) f32 linear constant terms by slot
    coeff: jax.Array          # (T, L, Km) f32 leaf coefficients
    coeff_feat: jax.Array     # (T, L, Km) i32 column index into X
    coeff_mask: jax.Array     # (T, L, Km) bool valid coefficient slots


def pack_splits(trees: List, num_class: int = 1) -> PackedSplits:
    """Pack host Tree models into device arrays (raw-value routing).
    Returns ``(pack, has_cat, has_linear)``."""
    T = max(len(trees), 1)
    arrs = [t.to_split_arrays() for t in trees] or \
        [dict(slot=np.zeros(0, np.int32), feature=np.zeros(0, np.int32),
              threshold=np.zeros(0), kind=np.zeros(0, np.int32),
              default_left=np.zeros(0, bool), missing_type=np.zeros(0, np.int32),
              cat_values={}, leaf_of_slot=np.zeros(1, np.int32))]
    R = max((len(a["slot"]) for a in arrs), default=0)
    R = max(R, 1)
    L = R + 1
    Kc = max((len(v) for a in arrs for v in a["cat_values"].values()),
             default=0)
    has_cat = Kc > 0
    Kc = max(Kc, 1)

    slot = np.zeros((T, R), np.int32)
    feature = np.zeros((T, R), np.int32)
    threshold = np.zeros((T, R), np.float32)
    kind = np.zeros((T, R), np.int32)
    default_left = np.zeros((T, R), bool)
    missing_type = np.zeros((T, R), np.int32)
    num_splits = np.zeros(T, np.int32)
    value_of_slot = np.zeros((T, L), np.float32)
    tree_class = np.zeros(T, np.int32)
    cat_values = np.full((T, R, Kc), -2, np.int64)
    for ti, (t, a) in enumerate(zip(trees, arrs)):
        r = len(a["slot"])
        num_splits[ti] = r
        tree_class[ti] = ti % num_class
        slot[ti, :r] = a["slot"]
        feature[ti, :r] = a["feature"]
        threshold[ti, :r] = a["threshold"]
        kind[ti, :r] = a["kind"]
        default_left[ti, :r] = a["default_left"]
        missing_type[ti, :r] = a["missing_type"]
        lv = t.leaf_value[a["leaf_of_slot"][:r + 1]] if t.num_leaves > 1 \
            else t.leaf_value[:1]
        value_of_slot[ti, :len(lv)] = lv
        for rr, cats in a["cat_values"].items():
            cat_values[ti, rr, :len(cats)] = cats
    from ..linear.pack import linear_pack_arrays
    const_of_slot, coeff, coeff_feat, coeff_mask, has_linear = \
        linear_pack_arrays(trees, arrs, value_of_slot)
    pk = PackedSplits(
        slot=jnp.asarray(slot, jnp.int32),
        feature=jnp.asarray(feature, jnp.int32),
        threshold=jnp.asarray(threshold, jnp.float32),
        kind=jnp.asarray(kind, jnp.int32),
        default_left=jnp.asarray(default_left, jnp.bool_),
        missing_type=jnp.asarray(missing_type, jnp.int32),
        num_splits=jnp.asarray(num_splits, jnp.int32),
        value_of_slot=jnp.asarray(value_of_slot, jnp.float32),
        tree_class=jnp.asarray(tree_class, jnp.int32),
        cat_values=jnp.asarray(cat_values, jnp.int32),
        const_of_slot=jnp.asarray(const_of_slot, jnp.float32),
        coeff=jnp.asarray(coeff, jnp.float32),
        coeff_feat=jnp.asarray(coeff_feat, jnp.int32),
        coeff_mask=jnp.asarray(coeff_mask, jnp.bool_))
    return pk, has_cat, has_linear


def _route_tree(X, tp, has_cat: bool):
    """Route all rows through one packed tree -> (N,) leaf slots."""
    n = X.shape[0]
    max_r = tp.slot.shape[0]

    def step(r, row_slot):
        active = r < tp.num_splits
        col = jnp.take(X, tp.feature[r], axis=1)
        mt = tp.missing_type[r]
        nan = jnp.isnan(col)
        v = jnp.where(nan & (mt != 2), 0.0, col)
        go = v <= tp.threshold[r]
        go = jnp.where((mt == 2) & nan, tp.default_left[r], go)
        go = jnp.where((mt == 1) & (jnp.abs(v) <= K_ZERO),
                       tp.default_left[r], go)
        if has_cat:
            iv = jnp.where(jnp.isfinite(col), col, -1.0).astype(jnp.int32)
            in_set = jnp.any(iv[:, None] == tp.cat_values[r][None, :], axis=1)
            go = jnp.where(tp.kind[r] > 0, in_set, go)
        upd = jnp.where((row_slot == tp.slot[r]) & ~go, r + 1, row_slot)
        return jnp.where(active, upd, row_slot)

    return jax.lax.fori_loop(0, max_r, step, jnp.zeros((n,), jnp.int32))


def predict_raw_impl(X: jax.Array, pack: PackedSplits, *, num_class: int = 1,
                     has_cat: bool = False, has_linear: bool = False,
                     tree_batch: int = 8, init_score=None) -> jax.Array:
    """(N, F) raw rows -> (N,) or (N, K) raw ensemble scores.

    Un-jitted body shared by the training-path ``predict_raw`` below and
    the serving path's shape-bucketed jit (serve/session.py) — both wrap
    it with their own ``jax.jit`` + ``track_jit`` label so compile counts
    stay attributable per entry point."""
    from ..learner import leaf_values_by_row
    from ..linear.pack import linear_values_by_row

    n = X.shape[0]
    X = X.astype(jnp.float32)
    T = pack.slot.shape[0]
    pad_t = (-T) % tree_batch
    if pad_t:
        pack = jax.tree.map(
            lambda a: jnp.concatenate(
                [a, jnp.zeros((pad_t,) + a.shape[1:], a.dtype)]), pack)
    num_l = pack.value_of_slot.shape[1]
    grouped = jax.tree.map(
        lambda a: a.reshape(-1, tree_batch, *a.shape[1:]), pack)

    def one_batch(score, tb):
        slots = jax.vmap(lambda tp: _route_tree(X, tp, has_cat))(tb)  # (tb, N)
        if has_linear:
            vals = jax.vmap(
                lambda tp, s: linear_values_by_row(X, s, tp, num_l))(
                    tb, slots)                                        # (tb, N)
        else:
            vals = jax.vmap(lambda lv, s: leaf_values_by_row(lv, s, num_l))(
                tb.value_of_slot, slots)                              # (tb, N)
        # unsplit and padding trees both carry all-zero slot values
        if num_class > 1:
            cls_oh = (tb.tree_class[:, None]
                      == jnp.arange(num_class, dtype=jnp.int32)[None, :]
                      ).astype(jnp.float32)
            score = score + vals.T @ cls_oh
        else:
            score = score + jnp.sum(vals, axis=0)
        return score, None

    shape = (n, num_class) if num_class > 1 else (n,)
    score0 = jnp.zeros(shape, jnp.float32)
    if init_score is not None:
        score0 = score0 + init_score
    score, _ = jax.lax.scan(one_batch, score0, grouped)
    return score


predict_raw = track_jit("ops/predict_raw", jax.jit(
    predict_raw_impl,
    static_argnames=("num_class", "has_cat", "has_linear", "tree_batch")))


def split_bin_table(a, dataset):
    """Per-split BIN-space routing quantities for one tree's
    ``to_split_arrays`` dict: the single conversion shared by
    ``tree_to_bin_log`` (go_left tables for ``assign_leaves``) and the
    forest repack (``ops/forest.py`` split-major node tables).

    Returns a dict of per-split arrays — ``feature`` (inner index),
    ``tbin`` (threshold bin: go left iff ``bin <= tbin``), ``miss_bin``/
    ``movable`` (missing-bin override), ``valid`` (False where the split
    feature has no inner index in the dataset) — plus ``cat_bins``
    mapping categorical split index -> bins routed LEFT."""
    from .binning import BIN_CATEGORICAL, MISSING_NAN, MISSING_ZERO

    r = len(a["slot"])
    feature = np.zeros(r, np.int32)
    tbin = np.zeros(r, np.int32)
    miss_bin = np.zeros(r, np.int32)
    movable = np.zeros(r, bool)
    valid = np.ones(r, bool)
    cat_bins = {}
    for i in range(r):
        inner = dataset.inner_feature_index(int(a["feature"][i]))
        if inner < 0:
            valid[i] = False
            continue
        m = dataset.bin_mappers[inner]
        feature[i] = inner
        if a["kind"][i]:
            cats = a["cat_values"].get(i, np.array([], np.int64))
            cat_bins[i] = np.flatnonzero(
                np.isin(m.categories, cats)).astype(np.int64)
        else:
            tb = int(np.searchsorted(m.upper_bounds, float(a["threshold"][i]),
                                     side="left"))
            tb = min(tb, m.num_bins - 1)
            tbin[i] = tb
            if m.missing_type in (MISSING_ZERO, MISSING_NAN) \
                    and m.bin_type != BIN_CATEGORICAL:
                miss_bin[i] = m.missing_bin
                movable[i] = True
    return dict(feature=feature, tbin=tbin, miss_bin=miss_bin,
                movable=movable, valid=valid, cat_bins=cat_bins)


def tree_to_bin_log(tree, dataset):
    """Convert a host Tree into a TreeLog-compatible record routing in BIN
    space over the dataset's (bundled) training matrix — lets DART score
    replay, rollback and continued-training valid replay reuse
    ``assign_leaves`` on device instead of walking trees in Python
    (reference analogs: dart.hpp score updates, gbdt.cpp:454
    RollbackOneIter)."""
    from ..learner import TreeLog

    a = tree.to_split_arrays()
    r = len(a["slot"])
    num_bin = int(dataset.feature_num_bins().max()) if dataset.num_features \
        else 1
    # pad split count to a power-of-two bucket so assign_leaves compiles a
    # handful of signatures instead of one per distinct tree size
    rp = 16
    while rp < r:
        rp *= 2
    tbl_r = split_bin_table(a, dataset)
    feature = np.zeros(rp, np.int32)
    tbin = np.zeros(rp, np.int32)
    kind = np.zeros(rp, np.int32)
    miss_bin = np.zeros(rp, np.int32)
    movable = np.zeros(rp, bool)
    go_left = np.zeros((rp, num_bin), bool)
    b_iota = np.arange(num_bin)
    feature[:r] = tbl_r["feature"]
    tbin[:r] = tbl_r["tbin"]
    miss_bin[:r] = tbl_r["miss_bin"]
    movable[:r] = tbl_r["movable"]
    for i in range(r):
        if not tbl_r["valid"][i]:
            continue
        if a["kind"][i]:
            kind[i] = 1
            go_left[i, tbl_r["cat_bins"][i]] = True
        else:
            tbl = b_iota <= tbin[i]
            if movable[i]:
                tbl = tbl.copy()
                tbl[miss_bin[i]] = bool(a["default_left"][i])
            go_left[i] = tbl
    slot = np.zeros(rp, np.int32)
    slot[:r] = a["slot"]
    default_left = np.zeros(rp, bool)
    default_left[:r] = a["default_left"]
    leaf_value = np.zeros(rp + 1, np.float32)
    leaf_value[:r + 1] = tree.leaf_value[a["leaf_of_slot"][:r + 1]] \
        if r else tree.leaf_value[:1]
    return TreeLog(
        num_splits=jnp.int32(r),
        split_leaf=jnp.asarray(slot, jnp.int32),
        feature=jnp.asarray(feature, jnp.int32),
        bin=jnp.asarray(tbin, jnp.int32),
        kind=jnp.asarray(kind, jnp.int32),
        default_left=jnp.asarray(default_left, jnp.bool_),
        gain=jnp.zeros(rp, jnp.float32),
        left_sum=jnp.zeros((rp, 3), jnp.float32),
        right_sum=jnp.zeros((rp, 3), jnp.float32),
        go_left=jnp.asarray(go_left, jnp.bool_),
        miss_bin=jnp.asarray(miss_bin, jnp.int32),
        movable=jnp.asarray(movable, jnp.bool_),
        leaf_value=jnp.asarray(leaf_value, jnp.float32),
        leaf_sum=jnp.zeros((rp + 1, 3), jnp.float32),
        row_leaf=jnp.zeros(1, jnp.int32),
    )
