"""Batched on-device prediction over packed tree arrays.

TPU-native replacement for the reference's per-row pointer walk
(reference: include/LightGBM/tree.h:133 Tree::Predict,
src/boosting/gbdt_prediction.cpp): the whole ensemble is packed into fixed
(T, nodes) arrays, rows are routed by repeated gathers under ``lax.scan``
over trees and ``lax.while_loop`` over depth — data-independent control
flow, fully jittable, row-shardable over a mesh.

Routing happens in BIN space: raw features are binned once (value->bin is a
per-feature searchsorted) and every split is a (B,) boolean table lookup.
This makes numerical/categorical/missing handling uniform — the same trick
the training partition uses.
"""
from __future__ import annotations

from typing import List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class PackedTrees(NamedTuple):
    """(T = trees, I = max internal nodes, B = max bins)"""
    feature: jax.Array     # (T, I) i32 inner feature index
    go_left: jax.Array     # (T, I, B) bool
    left: jax.Array        # (T, I) i32 child (neg = ~leaf)
    right: jax.Array       # (T, I) i32
    leaf_value: jax.Array  # (T, L) f32
    num_internal: jax.Array  # (T,) i32
    tree_class: jax.Array  # (T,) i32 — class id of each tree (multiclass)


def pack_trees(trees: List, dataset, num_bin: int, num_class: int = 1) -> PackedTrees:
    """Build the packed arrays from host Tree models + the dataset's bin
    mappers (bin tables absorb threshold/categorical/missing semantics)."""
    from ..ops.binning import BIN_CATEGORICAL, MISSING_NAN, MISSING_ZERO
    T = len(trees)
    L = max((t.num_leaves for t in trees), default=1)
    I = max(L - 1, 1)
    feature = np.zeros((T, I), np.int32)
    go_left = np.zeros((T, I, num_bin), bool)
    left = np.full((T, I), -1, np.int32)
    right = np.full((T, I), -1, np.int32)
    leaf_value = np.zeros((T, L), np.float32)
    num_internal = np.zeros(T, np.int32)
    tree_class = np.zeros(T, np.int32)
    b_iota = np.arange(num_bin)
    for ti, t in enumerate(trees):
        tree_class[ti] = ti % num_class
        leaf_value[ti, : t.num_leaves] = t.leaf_value
        num_internal[ti] = t.num_internal if t.num_leaves > 1 else 0
        if t.num_leaves <= 1:
            continue
        for nd in range(t.num_internal):
            real_f = int(t.split_feature[nd])
            inner = dataset.inner_feature_index(real_f)
            if inner < 0:
                inner = 0
                tbl = np.zeros(num_bin, bool)
            else:
                mapper = dataset.bin_mappers[inner]
                if t.decision_type[nd] & 1:
                    cats = t.cat_threshold.get(nd, np.array([], dtype=np.int64))
                    cat_of_bin = np.full(num_bin, -1, np.int64)
                    nc = len(mapper.categories)
                    cat_of_bin[:nc] = mapper.categories
                    tbl = np.isin(cat_of_bin, cats)
                else:
                    # threshold value -> bin: route by real threshold so models
                    # loaded from text (value thresholds) stay exact
                    thr = float(t.threshold[nd])
                    ub = mapper.upper_bounds
                    tbin = int(np.searchsorted(ub, thr, side="left"))
                    tbin = min(tbin, mapper.num_bins - 1)
                    tbl = b_iota <= tbin
                    if mapper.missing_type in (MISSING_NAN, MISSING_ZERO) \
                            and mapper.bin_type != BIN_CATEGORICAL:
                        tbl = tbl.copy()
                        tbl[mapper.missing_bin] = bool(t.decision_type[nd] & 2)
            feature[ti, nd] = inner
            go_left[ti, nd] = tbl
            left[ti, nd] = t.left_child[nd]
            right[ti, nd] = t.right_child[nd]
    return PackedTrees(
        feature=jnp.asarray(feature), go_left=jnp.asarray(go_left),
        left=jnp.asarray(left), right=jnp.asarray(right),
        leaf_value=jnp.asarray(leaf_value), num_internal=jnp.asarray(num_internal),
        tree_class=jnp.asarray(tree_class))


def predict_binned(bins: jax.Array, pack: PackedTrees, num_class: int = 1,
                   init_score: jax.Array = None) -> jax.Array:
    """(N, F) binned rows -> (N,) or (N, K) raw scores."""
    n = bins.shape[0]
    num_trees = pack.feature.shape[0]

    def one_tree(carry, tp):
        score = carry
        feat, tbl, lc, rc, lv, ni, cls = tp

        def routing_step(state):
            node, _ = state
            f = feat[jnp.maximum(node, 0)]
            b = jnp.take_along_axis(bins, f[:, None].astype(jnp.int32),
                                    axis=1)[:, 0].astype(jnp.int32)
            gl = tbl[jnp.maximum(node, 0), b]
            nxt = jnp.where(gl, lc[jnp.maximum(node, 0)], rc[jnp.maximum(node, 0)])
            node = jnp.where(node >= 0, nxt, node)
            return node, jnp.any(node >= 0)

        node0 = jnp.where(ni > 0, 0, -1) * jnp.ones((n,), jnp.int32)
        node, _ = jax.lax.while_loop(lambda s: s[1], routing_step,
                                     (node0, ni > 0))
        leaf = jnp.where(node < 0, ~node, 0)
        vals = lv[leaf]
        if num_class > 1:
            score = score.at[:, cls].add(vals)
        else:
            score = score + vals
        return score, None

    shape = (n, num_class) if num_class > 1 else (n,)
    score0 = jnp.zeros(shape, jnp.float32)
    if init_score is not None:
        score0 = score0 + init_score
    score, _ = jax.lax.scan(one_tree, score0, pack)
    return score


def bin_values_device(X: jax.Array, upper_bounds: jax.Array,
                      nan_bins: jax.Array, nan_missing: jax.Array) -> jax.Array:
    """Vectorized value->bin on device for numerical features:
    (N, F) raw + (F, Bmax) padded upper bounds -> (N, F) bins.
    (Categorical features are binned on host — dictionary lookup.)"""
    # searchsorted per feature via comparison count: bin = sum(ub < x)
    nan_mask = jnp.isnan(X)
    Xz = jnp.where(nan_mask & ~nan_missing[None, :], 0.0, X)
    bins = jnp.sum(Xz[:, :, None] > upper_bounds.T[None, :, :], axis=2)
    bins = jnp.where(nan_mask & nan_missing[None, :], nan_bins[None, :], bins)
    return bins.astype(jnp.int32)
