"""Pallas row-routing kernel: the whole tree's split log in one pass.

The XLA form of ``assign_leaves`` (learner.py) walks the split log with a
254-round ``fori_loop``, each round a full-N elementwise pass — ~30 ms/tree
at 2M rows (the per-round fusions are small and latency-bound). This kernel
streams each row tile through VMEM ONCE and applies all rounds in-register:
HBM traffic drops to one read of the transposed binned matrix plus one
write of the leaf vector, and the per-round work is a handful of VPU ops on
a resident (rows/128, 128) tile (~5 ms/tree).

Scope: numerical splits, with or without EFB bundles (all per-round
quantities reduce to SMEM scalars). Categorical splits need a per-row
(B,)-table lookup — those trees fall back to the XLA router.

Reference analog: Tree::PredictLeafIndex over pre-binned data
(src/io/tree.cpp), used for score updates via the data partition
(score_updater.hpp:88).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pallas is optional at import time (CPU meshes use the XLA path)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    if not hasattr(pltpu, "HBM"):  # older jax spells these differently
        pltpu.HBM = pltpu.ANY
        pltpu.CompilerParams = pltpu.TPUCompilerParams
except Exception:  # pragma: no cover
    pl = pltpu = None

# SMEM table layout: per round r the columns are
#   0 col      matrix column to read (bundle group or feature)
#   1 leaf     leaf id split this round
#   2 bin      threshold bin (feature-space)
#   3 miss     movable-missing bin (-1: none)
#   4 dl       default-left flag
#   5 plain    1 = no bundle arithmetic for this column
#   6 off      bundle: sub-feature's slot offset
#   7 dpos     bundle: shared default-bin slot position
#   8 nbm1     bundle: sub-feature slots (num_bins - 1)
#   9 rest     bundle: direction of out-of-range slots
TBL_W = 10
ROUTE_BLOCK_ROWS = 16384  # rows per grid block (shared with assign_leaves)


def _route_kernel(sref, binst_ref, out_ref, *, rounds, csub, num_feat):
    i32 = jnp.int32
    num_splits = sref[0]
    state = jnp.zeros((csub, 128), i32)

    def body(r, state):
        base = 1 + r * TBL_W
        col_idx = sref[base + 0]
        leaf = sref[base + 1]
        tbin = sref[base + 2]
        miss = sref[base + 3]
        dl = sref[base + 4]
        plain = sref[base + 5]
        off = sref[base + 6]
        dpos = sref[base + 7]
        nbm1 = sref[base + 8]
        rest = sref[base + 9]
        col = binst_ref[col_idx].astype(i32)           # (csub, 128)
        # bundle slot -> feature bin (identity when plain): slots above the
        # shared default position shift down by one. All routing flags stay
        # in i32 0/1 form — Mosaic cannot truncate i8 vectors to i1 data.
        rank = col - off
        fb = rank + jnp.clip(rank - dpos + 1, 0, 1)    # +1 when rank >= dpos
        in_r = jnp.clip(col - off + 1, 0, 1) \
            * jnp.clip(off + nbm1 - col, 0, 1)         # 1 when in range
        eff = jnp.where(plain == 1, col, fb)
        go = jnp.clip(tbin - eff + 1, 0, 1)            # 1 when eff <= tbin
        is_miss = 1 - jnp.clip(jnp.abs(eff - miss), 0, 1)
        go = jnp.where((miss >= 0) & (is_miss == 1), dl, go)
        go = jnp.where((plain == 1) | (in_r == 1), go, rest)
        upd = jnp.where((state == leaf) & (go == 0), r + 1, state)
        return jnp.where(r < num_splits, upd, state)

    state = jax.lax.fori_loop(0, rounds, body, state)
    out_ref[:, :] = state


def route_rows(bins_t: jax.Array, table: jax.Array, num_splits: jax.Array,
               n: int, *, rows_per_block: int = ROUTE_BLOCK_ROWS
               ) -> jax.Array:
    """(F, Npad/128, 128) u8 tiles + (R*TBL_W,) i32 table -> (Npad,) i32.

    ``bins_t`` must be the transposed binned matrix reshaped to
    (F, Npad/128, 128) with Npad a multiple of rows_per_block; padding rows
    route harmlessly (callers slice [:n]).
    """
    num_feat, nsub, _ = bins_t.shape
    rounds = (table.shape[0]) // TBL_W
    csub = rows_per_block // 128
    assert nsub % csub == 0, (nsub, csub)
    grid = nsub // csub
    scalars = jnp.concatenate([num_splits.reshape(1).astype(jnp.int32),
                               table.astype(jnp.int32)])
    kern = partial(_route_kernel, rounds=rounds, csub=csub,
                   num_feat=num_feat)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid,),
        in_specs=[pl.BlockSpec((num_feat, csub, 128),
                               lambda i, s: (0, i, 0))],
        out_specs=pl.BlockSpec((csub, 128), lambda i, s: (i, 0)),
    )
    from .partition import _INTERPRET
    out = pl.pallas_call(
        kern,
        name="route_rows",
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nsub, 128), jnp.int32),
        interpret=_INTERPRET,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(scalars, bins_t)
    return out.reshape(-1)


def build_route_table(log, meta, bundle: Optional[dict]) -> jax.Array:
    """Assemble the per-round SMEM scalar table from a TreeLog (in-graph;
    all gathers are over (R,)-sized arrays)."""
    r_iota = jnp.arange(log.split_leaf.shape[0], dtype=jnp.int32)
    feat = log.feature
    if bundle is not None:
        colv = bundle["group"][feat]
        plain = ~bundle["has_rest"][feat]
        off = bundle["offset"][feat]
        dpos = bundle["dpos"][feat]
        nbm1 = bundle["nbm1"][feat]
        rest = jnp.take_along_axis(
            log.go_left, dpos[:, None], axis=1)[:, 0]
    else:
        colv = feat
        plain = jnp.ones_like(feat, dtype=bool)
        off = jnp.zeros_like(feat)
        dpos = jnp.zeros_like(feat)
        nbm1 = jnp.zeros_like(feat)
        rest = jnp.zeros_like(feat, dtype=bool)
    miss = jnp.where(log.movable, log.miss_bin, -1)
    cols = [colv, log.split_leaf, log.bin, miss,
            log.default_left.astype(jnp.int32), plain.astype(jnp.int32),
            off, dpos, nbm1, rest.astype(jnp.int32)]
    del r_iota, meta
    return jnp.stack([c.astype(jnp.int32) for c in cols],
                     axis=1).reshape(-1)
