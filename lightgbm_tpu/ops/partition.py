"""Leaf-contiguous row partition, the device analog of DataPartition.

The reference keeps per-leaf row-index lists and stably partitions the
parent's indices on every split (reference: src/treelearner/
data_partition.hpp:101 Split, via ParallelPartitionRunner, threading.h:22).
That contract — per-split work proportional to the PARENT leaf, histograms
proportional to the CHILD leaf — is what makes 255-leaf trees affordable;
an O(N)-per-split design pays ~num_leaves/log(num_leaves) times more.

TPU-native form: rows are kept PHYSICALLY grouped by leaf in a packed
working buffer, so the histogram kernel streams a contiguous segment with
zero gathers (TPU row-gathers measured ~60ns/row — unusable; contiguous
dynamic slices run at HBM bandwidth). The working row layout is

    [ bins u8 x F | g f32 as 4 bytes | h f32 | cnt f32 ]   -> (Npad, F+12) u8

one array, one dtype: a split is ONE dynamic_slice per chunk, one in-chunk
compaction, two blended writes. f32 channels ride the compaction matmul as
their four u8 bytes — each byte is an integer <= 255, exactly representable
in bf16, so a 0/1 permutation matmul moves rows bit-exactly.

A split stably partitions the parent's segment [start, start+cnt):

- chunks of CH rows are compacted in-register via a (CH, CH) permutation
  one-hot matmul (MXU), left rows to the chunk front, right rows to the
  chunk back;
- compacted chunks are written with two cursors (left ascending from
  ``start``, right descending from ``start+cnt``) into the OTHER buffer of
  a ping-pong pair — children flip parity, nothing is copied back. Writes
  are blended read-modify-writes that touch only the valid rows, so the
  result is exact with no variable-length writes anywhere. The right
  child's rows land chunk-reversed — leaf row order is insignificant
  (histograms are order-free; sub-splits re-partition).

All ops are dynamic_slice / dynamic_update_slice / small matmuls — plain
XLA, so the same code runs on TPU, on the CPU test mesh, and inside
shard_map for the distributed learners.

Buffers carry a CH-row guard region at BOTH ends (rows live in
[GUARD, GUARD + n)) so slice windows never clamp.
"""
from __future__ import annotations

import math
import os
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..obs import trace_phase

try:  # pallas is optional at import time (CPU test meshes use the XLA path)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    if not hasattr(pltpu, "HBM"):  # pre-0.5 jax (CPU test meshes)
        pltpu.HBM = pltpu.ANY
        pltpu.CompilerParams = pltpu.TPUCompilerParams
except Exception:  # pragma: no cover
    pl = pltpu = None

# CPU-mesh validation hook: run the pallas kernels under the pallas
# interpreter (tests/test_work_layout.py). Kernels that read the dst plane
# through the ALIASED OUTPUT ref are bit-faithful under it (the interpreter
# honors input_output_aliases and performs DMAs at .start()).
_INTERPRET = os.environ.get("LGBTPU_PALLAS_INTERPRET", "") not in ("", "0")

DEFAULT_CH = 2048
GH_BYTES = 12   # g, h, cnt as f32 bytes
GH_BYTES_Q = 3  # quantized: g, h as int8 bits, cnt as u8

# Resident-state slim work buffer (tpu_resident_state): the bin planes stay
# put in ORIGINAL row order and the partition permutes only a route byte, an
# i32 row-index plane (4 byte-planes) and the g/h/c payload.
RST_ROUTE = 1                        # plane 0: split feature's bin byte
RST_RIDX = 4                         # planes 1..4: row index, LE byte planes
RST_GH_OFF = RST_ROUTE + RST_RIDX    # planes 5..16: g/h/c f32 bytes
RST_WIDTH = RST_GH_OFF + GH_BYTES


def guard_rows(ch: int = DEFAULT_CH) -> int:
    return ch


def pack_rows(bins: jax.Array, ghc: jax.Array) -> jax.Array:
    """(N, F) u8 + (N, 3) f32 -> (N, F+12) u8 packed working rows."""
    gb = jax.lax.bitcast_convert_type(ghc.astype(jnp.float32), jnp.uint8)
    return jnp.concatenate([bins, gb.reshape(ghc.shape[0], GH_BYTES)], axis=1)


def unpack_ghc(rows: jax.Array, num_feat: int) -> jax.Array:
    """(N, F+12) u8 packed rows -> (N, 3) f32 channels."""
    gb = rows[:, num_feat:num_feat + GH_BYTES].reshape(rows.shape[0], 3, 4)
    return jax.lax.bitcast_convert_type(gb, jnp.float32)


def pack_rows_quantized(bins: jax.Array, ghc: jax.Array, key: jax.Array,
                        gscale, hscale) -> jax.Array:
    """(N, F) u8 + (N, 3) f32 -> (N, F+3) u8 with int8-quantized gradients.

    Stochastic rounding (floor(x*scale + u), u ~ U[0,1)) keeps histogram
    sums unbiased — the LightGBM quantized-training recipe (NeurIPS'22;
    LightGBM 4.x use_quantized_grad) at 8 bits instead of 2-5.
    """
    n = ghc.shape[0]
    u = jax.random.uniform(key, (n, 2))
    gq = jnp.clip(jnp.floor(ghc[:, 0] * gscale + u[:, 0]), -127, 127) \
        .astype(jnp.int8)
    hq = jnp.clip(jnp.floor(ghc[:, 1] * hscale + u[:, 1]), -127, 127) \
        .astype(jnp.int8)
    cnt = ghc[:, 2].astype(jnp.uint8)
    qb = jnp.stack([jax.lax.bitcast_convert_type(gq, jnp.uint8),
                    jax.lax.bitcast_convert_type(hq, jnp.uint8), cnt], axis=1)
    return jnp.concatenate([bins, qb], axis=1)


def unpack_ghq(rows: jax.Array, num_feat: int):
    """(N, F+3) u8 packed rows -> int8 g, int8 h, u8 cnt columns."""
    gq = jax.lax.bitcast_convert_type(rows[:, num_feat], jnp.int8)
    hq = jax.lax.bitcast_convert_type(rows[:, num_feat + 1], jnp.int8)
    return gq, hq, rows[:, num_feat + 2]


def _compact_chunk(cw, go, valid):
    """Stable in-chunk compaction: left rows to the front, right rows to the
    back, invalid (out-of-segment) rows parked in the middle gap.

    cw: (CH, W) u8 packed rows; go/valid: (CH,) bool.
    Returns (cw', nl, nr).
    """
    ch = cw.shape[0]
    gl = go & valid
    gr = (~go) & valid
    # one fused (CH, 3) prefix scan instead of three (profiled: each scan
    # is a separate ~2 us reduce-window per chunk)
    flags = jnp.stack([gl, gr, ~valid], axis=1).astype(jnp.int32)
    ranks = jnp.cumsum(flags, axis=0) - flags
    lrank, rrank, irank = ranks[:, 0], ranks[:, 1], ranks[:, 2]
    nl = ranks[-1, 0] + flags[-1, 0]
    nr = ranks[-1, 1] + flags[-1, 1]
    dest = jnp.where(gl, lrank,
                     jnp.where(gr, ch - nr + rrank, nl + irank))
    # permutation one-hot: P[j, i] = (dest_i == j); compacted = P @ rows.
    # u8 payload bytes are integers <= 255: exact under a 0/1 bf16 matmul.
    iota = jnp.arange(ch, dtype=jnp.int32)
    perm = (dest[None, :] == iota[:, None]).astype(jnp.bfloat16)
    cw2 = jax.lax.dot(perm, cw.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    return cw2.astype(jnp.uint8), nl, nr


def partition_segment(
    work: jax.Array,     # (2, Npad, F+12) u8 ping-pong buffer pair
    src_plane: jax.Array,  # scalar i32 plane holding the parent's rows
    start: jax.Array,    # scalar i32 physical start (includes guard offset)
    cnt: jax.Array,      # scalar i32 physical rows in the segment
    feat: jax.Array,     # scalar i32 split feature
    go_left: jax.Array,  # (B,) bool bin routing table
    *,
    ch: int = DEFAULT_CH,
) -> Tuple[jax.Array, jax.Array]:
    """Stable-partition rows [start, start+cnt) of plane ``src_plane`` into
    plane ``1 - src_plane`` (children flip parity — the plane index is a
    traced scalar, so no lax.cond / buffer copy is ever needed).

    Returns (work, left_cnt): left child at [start, start+left_cnt),
    right child rows (unordered) at [start+left_cnt, start+cnt).
    """
    num_bin = go_left.shape[0]
    table = go_left.astype(jnp.float32)
    nchunks = (cnt + ch - 1) // ch
    width = work.shape[2]
    dst_plane = 1 - src_plane

    def body(i, carry):
        work, lcur, rcur = carry
        off = start + i * ch
        cw = jax.lax.dynamic_slice(work, (src_plane, off, 0),
                                   (1, ch, width))[0]
        col = jax.lax.dynamic_index_in_dim(cw, feat, axis=1,
                                           keepdims=False).astype(jnp.int32)
        # gather-free table lookup: one-hot contraction over the bin axis
        oh = (col[:, None] == jnp.arange(num_bin, dtype=jnp.int32)[None, :])
        go = (oh.astype(jnp.float32) @ table) > 0.5
        pos = off + jnp.arange(ch, dtype=jnp.int32)
        valid = pos < start + cnt
        cw2, nl, nr = _compact_chunk(cw, go, valid)

        # blended read-modify-writes touch only the valid rows: exact, no
        # branches (lax.cond here would force buffer copies and break XLA's
        # in-place aliasing of the fori carry)
        j = jnp.arange(ch, dtype=jnp.int32)[:, None]

        def blend_at(work, at, keep_left):
            cur = jax.lax.dynamic_slice(work, (dst_plane, at, 0),
                                        (1, ch, width))[0]
            m = (j < nl) if keep_left else (j >= ch - nr)
            return jax.lax.dynamic_update_slice(
                work, jnp.where(m, cw2, cur)[None], (dst_plane, at, 0))

        work = blend_at(work, lcur, True)
        work = blend_at(work, rcur - ch, False)
        return work, lcur + nl, rcur - nr

    with trace_phase("lgbtpu/ops/partition_segment"):
        work, lcur, _ = jax.lax.fori_loop(
            0, nchunks, body, (work, start, start + cnt))
        return work, lcur - start


# ---------------------------------------------------------------------------
# Transposed (W, N) work-plane layout
# ---------------------------------------------------------------------------
#
# The row-major buffer streams 128-lane rows of which only F+12 (~40) bytes
# are real — a ~3x lane-occupancy waste on every partition DMA and VPU
# convert (PERF.md wall-true attribution: partition is ~65% of the ~2.08
# ms/split cost). The planes layout stores the SAME packed bytes transposed,
#
#     work[p]: (W, Npad) u8 — plane w holds byte column w of every row
#
# so each 128-lane tile carries 128 rows of ONE byte column: no dead lanes.
# A segment is a contiguous LANE range; a split is still one dynamic slice
# per chunk + one compaction matmul + two blended writes, just transposed —
# and the compaction matmul contracts over W (~40) instead of the padded 128.
# Row identity per chunk (dest computation) matches _compact_chunk exactly,
# so the XLA planes path produces BIT-IDENTICAL trees to the rows path.


def pack_planes(bins: jax.Array, ghc: jax.Array) -> jax.Array:
    """(N, F) u8 + (N, 3) f32 -> (F+12, N) u8 plane-major working columns."""
    return pack_rows(bins, ghc).T


def unpack_ghc_planes(planes: jax.Array, num_feat: int) -> jax.Array:
    """(F+12, C) u8 planes -> (3, C) f32 channels."""
    gb = planes[num_feat:num_feat + GH_BYTES].reshape(3, 4, -1)
    return jax.lax.bitcast_convert_type(gb.transpose(0, 2, 1), jnp.float32)


def _compact_chunk_planes(cw, go, valid):
    """Transposed twin of :func:`_compact_chunk`: cw is (W, CH) planes;
    go/valid are (CH,) bool over the chunk's columns (rows of data).

    dest is computed identically, so the produced row ORDER matches the
    row-major path bit-for-bit (this is what makes trees bit-identical
    across layouts: f32 histogram accumulation order is preserved)."""
    ch = cw.shape[1]
    gl = go & valid
    gr = (~go) & valid
    flags = jnp.stack([gl, gr, ~valid], axis=1).astype(jnp.int32)
    ranks = jnp.cumsum(flags, axis=0) - flags
    lrank, rrank, irank = ranks[:, 0], ranks[:, 1], ranks[:, 2]
    nl = ranks[-1, 0] + flags[-1, 0]
    nr = ranks[-1, 1] + flags[-1, 1]
    dest = jnp.where(gl, lrank,
                     jnp.where(gr, ch - nr + rrank, nl + irank))
    # P[i, j] = (dest_i == j); compacted = planes @ P — the contraction runs
    # over the CH source columns, costing W*CH MACs/column (W ~ 40 real
    # bytes) instead of the rows path's 128-padded width
    iota = jnp.arange(ch, dtype=jnp.int32)
    perm = (dest[:, None] == iota[None, :]).astype(jnp.bfloat16)
    cw2 = jax.lax.dot(cw.astype(jnp.bfloat16), perm,
                      preferred_element_type=jnp.float32)
    return cw2.astype(jnp.uint8), nl, nr


def partition_segment_planes(
    work: jax.Array,     # (2, W, Npad) u8 ping-pong plane pair
    src_plane: jax.Array,
    start: jax.Array,    # scalar i32 physical start LANE (includes guard)
    cnt: jax.Array,
    feat: jax.Array,
    go_left: jax.Array,  # (B,) bool bin routing table
    *,
    ch: int = DEFAULT_CH,
) -> Tuple[jax.Array, jax.Array]:
    """Planes-layout :func:`partition_segment` (same contract, same row
    order — left child stable, right child chunk-reversed — bit-identical
    to the rows path)."""
    num_bin = go_left.shape[0]
    table = go_left.astype(jnp.float32)
    nchunks = (cnt + ch - 1) // ch
    w = work.shape[1]
    dst_plane = 1 - src_plane

    def body(i, carry):
        work, lcur, rcur = carry
        off = start + i * ch
        cw = jax.lax.dynamic_slice(work, (src_plane, 0, off),
                                   (1, w, ch))[0]           # (W, CH)
        col = jax.lax.dynamic_index_in_dim(cw, feat, axis=0,
                                           keepdims=False).astype(jnp.int32)
        oh = (col[:, None] == jnp.arange(num_bin, dtype=jnp.int32)[None, :])
        go = (oh.astype(jnp.float32) @ table) > 0.5
        pos = off + jnp.arange(ch, dtype=jnp.int32)
        valid = pos < start + cnt
        cw2, nl, nr = _compact_chunk_planes(cw, go, valid)

        j = jnp.arange(ch, dtype=jnp.int32)[None, :]

        def blend_at(work, at, keep_left):
            cur = jax.lax.dynamic_slice(work, (dst_plane, 0, at),
                                        (1, w, ch))[0]
            m = (j < nl) if keep_left else (j >= ch - nr)
            return jax.lax.dynamic_update_slice(
                work, jnp.where(m, cw2, cur)[None], (dst_plane, 0, at))

        work = blend_at(work, lcur, True)
        work = blend_at(work, rcur - ch, False)
        return work, lcur + nl, rcur - nr

    with trace_phase("lgbtpu/ops/partition_segment_planes"):
        work, lcur, _ = jax.lax.fori_loop(
            0, nchunks, body, (work, start, start + cnt))
        return work, lcur - start


def pack_planes_fold_root(work: jax.Array, bins: jax.Array, ghc: jax.Array,
                          guard, *, num_bins: int, exact: bool, chunk: int,
                          lo_w: int = 0):
    """Planes pack pass with the root-node histogram FOLDED IN.

    One chunked loop reads (bins, ghc) once, writes the transposed planes
    into ``work[0][:, guard + i*chunk : ...]`` and accumulates the root
    histogram from the SAME row-major chunk — iteration 0 never re-reads
    the packed matrix. Chunk boundaries and masking replicate
    hist16_segment(work, 0, guard, n) exactly, so the folded histogram is
    bit-identical to the rows path's root pass.

    Returns (work, (F, num_bins, 3) root histogram) — LOCAL, callers
    reduce via comm.hist like any other segment histogram.
    """
    from .histogram import _hist16_chunk, _hist16_combine, auto_lo_w

    n, f = bins.shape
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    nchunks = (n + chunk - 1) // chunk
    npc = nchunks * chunk
    binsp = jnp.pad(bins, ((0, npc - n), (0, 0)))
    ghcp = jnp.pad(ghc, ((0, npc - n), (0, 0)))

    def body(i, carry):
        work, acc = carry
        off = i * chunk
        cb = jax.lax.dynamic_slice(binsp, (off, 0), (chunk, f))
        cg = jax.lax.dynamic_slice(ghcp, (off, 0), (chunk, 3))
        valid = jnp.arange(chunk, dtype=jnp.int32) < n - off
        cgm = cg * valid[:, None].astype(jnp.float32)
        acc = acc + _hist16_chunk(cb, cgm, num_bins, exact, lo_w)
        gb = jax.lax.bitcast_convert_type(cg, jnp.uint8) \
            .reshape(chunk, GH_BYTES)
        cw_t = jnp.concatenate([cb, gb], axis=1).T          # (W, chunk)
        work = jax.lax.dynamic_update_slice(
            work, cw_t[None], (0, 0, guard + off))
        return work, acc

    work, acc = jax.lax.fori_loop(
        0, nchunks, body,
        (work, jnp.zeros((f, sh, lo_w * nch), jnp.float32)))
    return work, _hist16_combine(acc, num_bins, exact, lo_w)


# ---------------------------------------------------------------------------
# Resident permuted state: partition a row-index plane, not the packed row
# ---------------------------------------------------------------------------
#
# The planes partition rewrites every plane of the work buffer per split —
# bins AND g/h/c. With tpu_resident_state the bin planes live ONCE in a
# (F, Npad) resident buffer in original row order, and the slim work buffer
# carries only [route | ridx x4 | g/h/c x12] = 17 planes. Before each
# partition a chunked gather pass writes the split feature's resident bin
# byte into the route plane (write_route_plane); partition_segment_planes
# and partition_segment_planes_fused then run UNCHANGED with feat=0,
# inheriting the Mosaic path (circular f32 stages, 128-aligned pure-write
# flushes, scalar-prefetched routing table) and — because the gathered
# route byte equals the leaf-order bin column value-for-value — the exact
# _compact_chunk_planes dest arithmetic, so trees stay bit-identical.
# Segment histograms gather the bin planes through the permuted row-index
# plane (hist16_segment_resident) with the planes path's chunking and f32
# accumulation order.


def resident_bin_planes(bins: jax.Array, guard, npad: int) -> jax.Array:
    """(N, F) u8 grouped bins -> (F, npad) u8 resident planes, original row
    i at lane guard + i. Written once per dataset; never re-partitioned."""
    res = jnp.zeros((bins.shape[1], npad), jnp.uint8)
    return jax.lax.dynamic_update_slice(res, bins.T, (0, guard))


def _decode_ridx(planes: jax.Array, npad: int) -> jax.Array:
    """(4, C) u8 LE byte-planes -> (C,) i32 row indices, clamped to the
    buffer. Lanes outside the live segment hold stale dst-parity bytes that
    can decode to anything (including negative i32); the clamp keeps the
    gather in bounds — every consumer valid-masks those lanes anyway."""
    b = planes.astype(jnp.int32)
    ridx = b[0] + b[1] * 256 + b[2] * 65536 + b[3] * 16777216
    return jnp.clip(ridx, 0, npad - 1)


def _encode_ridx(pos: jax.Array) -> jax.Array:
    """(C,) i32 -> (4, C) u8 little-endian byte planes."""
    sh = jnp.arange(RST_RIDX, dtype=jnp.int32)[:, None] * 8
    return ((pos[None, :] >> sh) & 255).astype(jnp.uint8)


def write_route_plane(work: jax.Array, resident: jax.Array, plane, start,
                      cnt, feat, *, ch: int = DEFAULT_CH) -> jax.Array:
    """Write the split feature's bin byte for each segment row into the
    route plane (plane 0) of the slim work buffer's ``plane`` parity.

    Decodes the permuted row-index planes on the SAME chunk grid the
    partition uses and gathers the feature's resident bin plane — the
    result is value-for-value the routing column the planes layout reads
    from its leaf-order work buffer, so the planes partition runs unchanged
    with feat=0. O(parent): ~6 bytes/row (4 ridx read + 1 gather read +
    1 route write) against the planes path's full-width read.
    """
    npad = work.shape[2]
    col = jax.lax.dynamic_index_in_dim(resident, feat, axis=0, keepdims=False)
    nchunks = (cnt + ch - 1) // ch

    def body(i, work):
        off = start + i * ch
        rb = jax.lax.dynamic_slice(work, (plane, RST_ROUTE, off),
                                   (1, RST_RIDX, ch))[0]
        route = jnp.take(col, _decode_ridx(rb, npad), axis=0)
        return jax.lax.dynamic_update_slice(
            work, route[None, None, :], (plane, 0, off))

    return jax.lax.fori_loop(0, nchunks, body, work)


def pack_resident_fold_root(work: jax.Array, bins: jax.Array, ghc: jax.Array,
                            guard, *, num_bins: int, exact: bool, chunk: int,
                            lo_w: int = 0):
    """Resident-state pack pass with the root histogram folded in.

    Mirrors :func:`pack_planes_fold_root` chunk-for-chunk (same
    _hist16_chunk accumulation order -> bit-identical root histogram) but
    writes the SLIM planes: a zeroed route plane, row-index byte planes
    holding ABSOLUTE lane positions (guard offset included, so gathers need
    no offset arithmetic), and the g/h/c bytes. The bin planes live in the
    resident buffer and are never packed.
    """
    from .histogram import _hist16_chunk, _hist16_combine, auto_lo_w

    n, f = bins.shape
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    nchunks = (n + chunk - 1) // chunk
    npc = nchunks * chunk
    binsp = jnp.pad(bins, ((0, npc - n), (0, 0)))
    ghcp = jnp.pad(ghc, ((0, npc - n), (0, 0)))

    def body(i, carry):
        work, acc = carry
        off = i * chunk
        cb = jax.lax.dynamic_slice(binsp, (off, 0), (chunk, f))
        cg = jax.lax.dynamic_slice(ghcp, (off, 0), (chunk, 3))
        valid = jnp.arange(chunk, dtype=jnp.int32) < n - off
        cgm = cg * valid[:, None].astype(jnp.float32)
        acc = acc + _hist16_chunk(cb, cgm, num_bins, exact, lo_w)
        pos = guard + off + jnp.arange(chunk, dtype=jnp.int32)
        gb = jax.lax.bitcast_convert_type(cg, jnp.uint8) \
            .reshape(chunk, GH_BYTES)
        cw_t = jnp.concatenate([jnp.zeros((RST_ROUTE, chunk), jnp.uint8),
                                _encode_ridx(pos), gb.T], axis=0)
        work = jax.lax.dynamic_update_slice(
            work, cw_t[None], (0, 0, guard + off))
        return work, acc

    work, acc = jax.lax.fori_loop(
        0, nchunks, body,
        (work, jnp.zeros((f, sh, lo_w * nch), jnp.float32)))
    return work, _hist16_combine(acc, num_bins, exact, lo_w)


# ---------------------------------------------------------------------------
# Fused Pallas kernel: the whole per-split pipeline in one device call
# ---------------------------------------------------------------------------
#
# partition_segment is ~10 XLA ops per chunk; at 2048-row chunks the fixed
# per-op cost (~19 us/chunk profiled) dominates the actual work (~4 us).
# A 255-leaf tree partitions ~5.6k chunks, so the op soup costs ~100 ms per
# tree at 2M rows — the single largest line in the round-2 profile. The
# Pallas kernel runs ONE call per split: an in-kernel chunk loop with
# manual HBM<->VMEM DMA and the route/rank/permute math on the MXU.
#
# v2 design (round 4; ~3x the v1 kernel, measured 1.7-2.4 vs 5-8 ns/row
# interleaved at the bench shape):
# - compaction permutation matmuls run per SB=256-row sub-block instead of
#   per CH-row chunk — the perm matmul costs SB*W MACs/row, so sub-blocks
#   cut the dominant MXU term ~4x;
# - left/right frontier rows accumulate in circular VMEM stages (2*CH
#   logical rows + CH of wrap margin) and flush to HBM as ALIGNED PURE
#   WRITES of whole CH-row tiles — v1 paid a read-modify-write of ~CH+32
#   rows on BOTH sides of every chunk plus a serializing lout.wait();
# - aligned-edge neighbor bytes prefill once per call; the final sub-CH
#   leftovers drain as full tiles plus one overlapping RMW tile.
# Row order inside a leaf is insignificant (histograms are order-free;
# sub-splits re-partition), so the kernel guarantees the row SET per side,
# byte-preserving neighbors outside [start, start+cnt).


ALIGN = 32  # Mosaic requires u8 DMA row offsets provably 32-aligned
PLANE_ALIGN = 128  # planes layout: lane-dim DMA offsets are whole 128-lane tiles
TABLE_WORDS = 8  # (B<=256,) bool routing table bit-packed into i32 scalars


def pack_table_bits(go_left: jax.Array) -> jax.Array:
    """(B,) bool -> (TABLE_WORDS,) i32 bit-packed (bit b of word w = bin
    32*w + b). Rides the kernel's scalar prefetch — full-array VMEM-spec
    pallas inputs trigger a device-wide ~400 us/op dispatch mode."""
    b = go_left.shape[0]
    bits = go_left
    if b < 32 * TABLE_WORDS:
        bits = jnp.pad(bits, (0, 32 * TABLE_WORDS - b))
    bits = bits.reshape(TABLE_WORDS, 32).astype(jnp.int32)
    weights = jnp.left_shift(jnp.int32(1), jnp.arange(32, dtype=jnp.int32))
    return jnp.sum(bits * weights[None, :], axis=1, dtype=jnp.int32)


def work_spec(num_groups: int, quantized: bool, part_kernel: str,
              part_chunk: int, hist_chunk: int, layout: str = "rows"):
    """(guard, width) of the packed ping-pong working buffer.

    Single source of truth shared by the tree builder and the fused
    trainer's carried-buffer allocation. Row-major layout: ``width`` is the
    packed row width (the fused pallas kernel needs 128-lane rows and
    guards covering aligned windows up to ALIGN rows past a segment).
    Planes layout: ``width`` is the PLANE count (sublane dim of the
    (2, W, Npad) buffer; the pallas kernel needs whole 32-sublane u8 tiles
    and guards covering 128-lane-aligned windows — see planes_npad for the
    lane-dim padding).
    """
    width = num_groups + (GH_BYTES_Q if quantized else GH_BYTES)
    guard = max(part_chunk, hist_chunk)
    if layout in ("planes", "resident"):
        if layout == "resident":
            width = RST_WIDTH    # slim payload; bin planes live elsewhere
        if part_kernel == "pallas":
            width = 32 * ((width + 31) // 32)  # whole u8 sublane tiles
            guard += 2 * PLANE_ALIGN
        return guard, width
    if part_kernel == "pallas":
        width = 128 * ((width + 127) // 128)   # whole 128-lane DMA tiles
        guard += 2 * ALIGN
    return guard, width


def goss_compact_rows(n: int, top_rate: float, other_rate: float) -> int:
    """Static compact-row count M for GOSS device compaction.

    top_k rows survive deterministically; of the remaining ``rest`` each
    survives independently with p = other_rate / (1 - top_rate), so the
    surviving count is top_k + Binomial(rest, p). M adds a 4-sigma margin
    (+32 slack for tiny shapes) so the in-graph compact/dense cond takes
    the compact branch for essentially every draw; the rare overflow
    (and every GOSS warmup iteration, which samples ALL rows) falls back
    to the verbatim dense-mask path inside the same jitted graph. M is a
    pure function of (n, rates) — shapes stay bucket-stable and the
    zero-recompile contract holds.
    """
    top_k = max(1, int(n * top_rate))
    rest = max(0, n - top_k)
    p = min(1.0, other_rate / max(1e-12, 1.0 - top_rate))
    slack = 4.0 * math.sqrt(rest * p * (1.0 - p)) + 32.0
    return min(n, top_k + int(math.ceil(rest * p + slack)))


def compact_rows_by_inbag(bins: jax.Array, ghc: jax.Array, m: int):
    """Gather the first M in-bag rows (original relative order) to the top.

    Returns (bins[:M] packed, ghc[:M] packed, in-bag count C). The sort key
    is the integer ``row + n*outbag`` — distinct per row, so argsort is
    order-deterministic without relying on a stable-sort kwarg: in-bag rows
    first, each side in original row order. When C > M the gather is
    truncated (caller must take the dense branch — checked via C).
    """
    n = bins.shape[0]
    inbag = ghc[:, 2] > 0
    iota = jnp.arange(n, dtype=jnp.int32)
    order = jnp.argsort(jnp.where(inbag, iota, iota + n))
    idx = jax.lax.slice_in_dim(order, 0, m)
    return (jnp.take(bins, idx, axis=0), jnp.take(ghc, idx, axis=0),
            jnp.sum(inbag.astype(jnp.int32)))


def planes_npad(n: int, guard: int, part_kernel: str = "xla") -> int:
    """Lane count of the planes work buffer: segment lanes + guards, padded
    to whole 128-lane tiles when the pallas kernel DMAs it."""
    npad = n + 2 * guard
    if part_kernel == "pallas":
        npad = 128 * ((npad + 127) // 128)
    return npad


def _partition_kernel(sref, work_in, work_ref, lt_ref,
                      tril, cin, pre, lstage, rstage, lfb, rfb, sem,
                      *, ch, sb, width, num_bin):
    f32 = jnp.float32
    lcap = 2 * ch
    nsub = ch // sb
    src_plane = sref[0]
    start = sref[1]
    cnt = sref[2]
    feat = sref[3]
    dst_plane = 1 - src_plane

    def a32(x):
        # Mosaic must PROVE u8 DMA row offsets divisible by the sublane
        # tiling; loop-carried multiples of 32 are not provable, so every
        # HBM offset is re-derived as (x // 32) * 32 at the use site.
        return (x // ALIGN) * ALIGN

    lbase0 = (start // ALIGN) * ALIGN
    head_l = start - lbase0                      # 0..31 neighbor rows below
    end = start + cnt
    rtop = ((end - 1) // ALIGN) * ALIGN          # rbase0 - ALIGN, provable
    rbase0 = rtop + ALIGN
    tail_r = rbase0 - end                        # 0..31 neighbor rows above

    astart = lbase0
    head = head_l
    tot = head + cnt
    nchunks = (tot + ch - 1) // ch

    # strict lower-triangular ones: ranks[i] = sum_{j<i} flags[j].
    # Arithmetic construction (clamped integer difference) — boolean
    # selects hit Mosaic relayout limits on i1 vectors.
    row_i = jax.lax.broadcasted_iota(jnp.int32, (sb, sb), 0)
    col_i = jax.lax.broadcasted_iota(jnp.int32, (sb, sb), 1)
    tril[:] = jnp.clip(row_i - col_i, 0, 1).astype(f32).astype(jnp.bfloat16)

    iota_sb = jax.lax.broadcasted_iota(jnp.int32, (sb, 1), 0)
    lane_w = jax.lax.broadcasted_iota(jnp.int32, (ch, width), 1)
    sub_i = jax.lax.broadcasted_iota(jnp.int32, (ch, 1), 0)

    # ---- prefills: neighbor rows of the aligned edge tiles ----
    pl_in = pltpu.make_async_copy(
        work_in.at[dst_plane, pl.ds(lbase0, ALIGN), :], pre.at[0], sem.at[2])
    pl_in.start()
    pr_in = pltpu.make_async_copy(
        work_in.at[dst_plane, pl.ds(rtop, ALIGN), :], pre.at[1], sem.at[3])
    pr_in.start()

    def start_in(i, slot):
        pltpu.make_async_copy(
            work_in.at[src_plane, pl.ds(a32(astart + i * ch), ch), :],
            cin.at[slot], sem.at[slot]).start()

    start_in(0, 0)

    pl_in.wait()
    lstage[0:ALIGN, :] = pre[0].astype(jnp.int32).astype(f32)
    pr_in.wait()
    rstage[ch - ALIGN:ch, :] = pre[1].astype(jnp.int32).astype(f32)

    def flush(stage, fb, flushed, left, sem_base):
        """Convert the ready CH-row stage half, start its pure HBM write."""
        half = jax.lax.rem(flushed // ch, 2)
        slot = half
        nflush = flushed // ch

        # slot reuse: wait the DMA issued 2 flushes ago (size-matched
        # reconstruction; .wait() only consumes the semaphore)
        @pl.when(nflush >= 2)
        def _():
            pltpu.make_async_copy(
                fb.at[slot], work_ref.at[dst_plane, pl.ds(0, ch), :],
                sem.at[sem_base + slot]).wait()
        hs = (half * ch // 8) * 8  # == half*ch; the pattern proves alignment
        fb[slot] = stage[pl.ds(hs, ch)].astype(jnp.int32) \
            .astype(jnp.uint8)
        if left:
            at = a32(lbase0 + flushed)
        else:
            at = a32(rbase0 - flushed - ch)
        pltpu.make_async_copy(
            fb.at[slot], work_ref.at[dst_plane, pl.ds(at, ch), :],
            sem.at[sem_base + slot]).start()

    iota_sb8 = jax.lax.broadcasted_iota(jnp.int32, (sb + 8, 1), 0)

    def append(stage, out8, n, ws, dlt, fill_sel_left):
        """Blend `n` compacted rows into the circular stage at window ws.

        Mosaic requires dynamic sublane window offsets provably 8-aligned
        for wide loads, so the window is [ws8, ws8 + sb + 8) with
        ws8 = align8(ws); ``out8`` rows are pre-shifted by dlt = ws - ws8
        (the permutation matmul absorbs the shift into its dest indices).
        """
        ws8 = (ws // 8) * 8
        win = stage[pl.ds(ws8, sb + 8)]
        if fill_sel_left:
            m = (iota_sb8 >= dlt) & (iota_sb8 < dlt + n)
        else:
            m = (iota_sb8 >= dlt + sb - n) & (iota_sb8 < dlt + sb)
        stage[pl.ds(ws8, sb + 8)] = jnp.where(m, out8, win)

        @pl.when(ws + sb > lcap)
        def _():
            # wrap: append dests in the margin [lcap, ws+sb) are logical
            # [0, ov). Blend ONLY those — on the descending (right) side
            # the rows at [ov, sb) hold current, not-yet-flushed data, and
            # the 8-row alignment pad beyond ws+sb holds stale bytes.
            ov = ws + sb - lcap
            stage[0:sb, :] = jnp.where(iota_sb < ov,
                                       stage[lcap:lcap + sb, :],
                                       stage[0:sb, :])

    def body(i, carry):
        p_l, p_r, fl_l, fl_r = carry
        slot = jax.lax.rem(i, 2)
        pltpu.make_async_copy(
            work_in.at[src_plane, pl.ds(a32(astart + i * ch), ch), :],
            cin.at[slot], sem.at[slot]).wait()

        @pl.when(i + 1 < nchunks)
        def _():
            start_in(i + 1, 1 - slot)

        # Mosaic has no direct u8<->f32 casts; bounce through i32
        cf = cin[slot].astype(jnp.int32).astype(f32)          # (CH, W)
        col = jnp.sum(jnp.where(lane_w == feat, cf, 0.0), axis=1,
                      keepdims=True)                          # (CH, 1)
        # routing table lookup: the (B,) bool table rides the scalar
        # prefetch as 8 bit-packed i32 words (a full-array VMEM-spec input
        # here put the WHOLE device into a ~400 us/op dispatch mode —
        # measured in scripts/spec_bisect.py — and poisoned every
        # subsequent op in the process, pallas or XLA alike)
        coli = col.astype(jnp.int32)
        word = jax.lax.shift_right_logical(coli, 5)
        wvals = jnp.zeros((ch, 1), jnp.int32)
        for w in range(TABLE_WORDS):
            wvals = jnp.where(word == w, sref[4 + w], wvals)
        bit = jnp.bitwise_and(coli, 31)
        go = jnp.bitwise_and(
            jax.lax.shift_right_logical(wvals, bit), 1) > 0
        pos = sub_i + i * ch
        valid = (pos >= head) & (pos < tot)                   # (CH, 1)

        for s in range(nsub):
            sub = cf[s * sb:(s + 1) * sb]                     # (SB, W)
            gl = go[s * sb:(s + 1) * sb] & valid[s * sb:(s + 1) * sb]
            gr = (~go[s * sb:(s + 1) * sb]) & valid[s * sb:(s + 1) * sb]
            flags = jnp.concatenate(
                [gl.astype(jnp.bfloat16), gr.astype(jnp.bfloat16)], axis=1)
            ranks = jax.lax.dot(tril[:], flags,
                                preferred_element_type=f32)   # (SB, 2)
            nl = jnp.sum(gl.astype(jnp.int32))
            nr = jnp.sum(gr.astype(jnp.int32))
            lrank = ranks[:, 0:1].astype(jnp.int32)
            rrank = ranks[:, 1:2].astype(jnp.int32)
            ws_l = jax.lax.rem(p_l, lcap)
            dlt_l = ws_l - (ws_l // 8) * 8
            # window start (CH - p_r - SB) mod LCAP, kept positive before
            # rem (lax.rem keeps the dividend's sign)
            ws_r = jax.lax.rem(ch - jax.lax.rem(p_r, lcap) - sb + 2 * lcap,
                               lcap)
            dlt_r = ws_r - (ws_r // 8) * 8
            # left rows rank to the window front; right rows to window
            # offsets sb-1-rrank (descending cursor); unrouted rows get -1;
            # dests shift by the window's 8-row alignment remainder
            dest_l = jnp.where(gl, lrank + dlt_l, -1)
            dest_r = jnp.where(gr, sb - 1 - rrank + dlt_r, -1)
            j_i = jax.lax.broadcasted_iota(jnp.int32, (sb + 8, sb), 0)
            perm_l = (1 - jnp.clip(jnp.abs(j_i - dest_l.reshape(1, sb)),
                                   0, 1)).astype(f32).astype(jnp.bfloat16)
            perm_r = (1 - jnp.clip(jnp.abs(j_i - dest_r.reshape(1, sb)),
                                   0, 1)).astype(f32).astype(jnp.bfloat16)
            # u8 payload bytes are integers <= 255: exact under a 0/1 bf16
            # permutation matmul with f32 accumulation
            sub_bf = sub.astype(jnp.bfloat16)
            out_l = jax.lax.dot(perm_l, sub_bf, preferred_element_type=f32)
            out_r = jax.lax.dot(perm_r, sub_bf, preferred_element_type=f32)

            append(lstage, out_l, nl, ws_l, dlt_l, True)
            p_l = p_l + nl

            @pl.when(p_l - fl_l >= ch)
            def _():
                flush(lstage, lfb, fl_l, True, 4)
            fl_l = jnp.where(p_l - fl_l >= ch, fl_l + ch, fl_l)

            append(rstage, out_r, nr, ws_r, dlt_r, False)
            p_r = p_r + nr

            @pl.when(p_r - fl_r >= ch)
            def _():
                flush(rstage, rfb, fl_r, False, 6)
            fl_r = jnp.where(p_r - fl_r >= ch, fl_r + ch, fl_r)

        return p_l, p_r, fl_l, fl_r

    p_l, p_r, fl_l, fl_r = jax.lax.fori_loop(
        0, nchunks, body, (head_l, tail_r, jnp.int32(0), jnp.int32(0)))

    # ---- drain leftovers: [lbase0+fl_l, rbase0-fl_r), all 32-aligned ----
    fill_l = p_l - fl_l
    fill_r = p_r - fl_r
    d = fill_l + fill_r
    dstart = lbase0 + fl_l

    # wait outstanding flush DMAs (the drain RMW tile may read their rows,
    # and kernel exit requires drained semaphores). The reconstruction uses
    # lfb for both sides — only the semaphore index and byte count matter.
    for base, fl in ((4, fl_l), (6, fl_r)):
        nf = fl // ch
        for back in (1, 2):
            @pl.when(nf >= back)
            def _(base=base, nf=nf, back=back):
                pltpu.make_async_copy(
                    lfb.at[jax.lax.rem(nf - back, 2)],
                    work_ref.at[dst_plane, pl.ds(0, ch), :],
                    sem.at[base + jax.lax.rem(nf - back, 2)]).wait()

    def read_circ(stage, qstart):
        """(ch, W) rows of the circular stage starting at logical qstart.
        Robust to any-sign qstart (true mathematical mod). The load is
        8-aligned (Mosaic wide-load constraint); the remainder is absorbed
        by a roll."""
        qs = jax.lax.rem(jax.lax.rem(qstart, lcap) + lcap, lcap)
        qs8 = (qs // 8) * 8
        dlt = qs - qs8
        a = pltpu.roll(stage[pl.ds(qs8, ch + 8)], -dlt, 0)[:ch]
        b = stage[pl.ds(0, ch)]
        lim = lcap - qs
        rolled = pltpu.roll(b, lim, 0)
        return jnp.where(sub_i[:ch] < lim, a, rolled)

    qr0 = jax.lax.rem(ch - jax.lax.rem(p_r, lcap) + 2 * lcap, lcap)

    def drain_tile(o):
        """(ch, W) drain rows for drain offsets [o, o+ch)."""
        lrows = read_circ(lstage, fl_l + o)
        rrows = read_circ(rstage, qr0 + (o - fill_l))
        off = sub_i[:ch] + o
        return jnp.where(off < fill_l, lrows, rrows)

    nfull = d // ch
    # d < 2*(ch+sb): at the default sb <= ch/2 that is <= 3*ch (nfull <= 2);
    # at part_chunk <= 256 sb == ch and the bound is 4*ch (nfull <= 3) —
    # MAXT must cover BOTH, so 4 is load-bearing, not slack
    MAXT = 4

    def dbody(t, _):
        @pl.when(t < nfull)
        def _():
            slot = jax.lax.rem(t, 2)

            @pl.when(t >= 2)
            def _():
                pltpu.make_async_copy(
                    lfb.at[slot], work_ref.at[dst_plane, pl.ds(0, ch), :],
                    sem.at[4 + slot]).wait()
            lfb[slot] = drain_tile(t * ch).astype(jnp.int32).astype(jnp.uint8)
            pltpu.make_async_copy(
                lfb.at[slot], work_ref.at[dst_plane,
                                          pl.ds(a32(dstart + t * ch), ch), :],
                sem.at[4 + slot]).start()
        return 0

    jax.lax.fori_loop(0, MAXT, dbody, 0)
    for back in range(1, 3):
        @pl.when(nfull >= back)
        def _(back=back):
            pltpu.make_async_copy(
                lfb.at[jax.lax.rem(nfull - back, 2)],
                work_ref.at[dst_plane, pl.ds(0, ch), :],
                sem.at[4 + jax.lax.rem(nfull - back, 2)]).wait()

    rem_ = d - nfull * ch

    @pl.when(rem_ > 0)
    def _():
        # one overlapping RMW tile ending exactly at the region end: rows
        # with drain offset in [nfull*ch, d) are fresh; below that the RMW
        # re-reads what full tiles just wrote (identical) or, when d < ch,
        # pre-segment bytes that must be preserved
        at = a32(dstart + d - ch)
        # read via the OUTPUT ref: on TPU it aliases work_in, but interpret
        # mode keeps distinct buffers and only work_ref holds the rows the
        # full drain tiles just wrote (planes kernel precedent, line ~1205)
        rd = pltpu.make_async_copy(
            work_ref.at[dst_plane, pl.ds(at, ch), :], lfb.at[0], sem.at[4])
        rd.start()
        rd.wait()
        tile = drain_tile(d - ch)
        old = lfb[0].astype(jnp.int32).astype(f32)
        off = sub_i[:ch] + (d - ch)
        keep_new = (off >= jnp.int32(nfull) * ch) & (off >= 0)
        merged = jnp.where(keep_new, tile, old)
        lfb[0] = merged.astype(jnp.int32).astype(jnp.uint8)
        wr = pltpu.make_async_copy(
            lfb.at[0], work_ref.at[dst_plane, pl.ds(at, ch), :], sem.at[4])
        wr.start()
        wr.wait()

    lt_ref[0] = p_l - head_l


def partition_segment_fused(
    work: jax.Array,       # (2, Npad, W) u8 ping-pong buffer pair
    src_plane: jax.Array,
    start: jax.Array,
    cnt: jax.Array,
    feat: jax.Array,
    go_left: jax.Array,    # (B,) bool
    *,
    ch: int = DEFAULT_CH,
    sb: int = 256,
) -> Tuple[jax.Array, jax.Array]:
    """Pallas form of :func:`partition_segment` (same contract, except row
    order WITHIN each side is unspecified — insignificant for this
    framework: histograms are order-free and sub-splits re-partition).

    Requires the work buffer's row width padded to 128 (DMA slices must
    cover whole 128-lane tiles) and guard regions of at least ch + 32 rows
    (edge tiles and input reads extend past the segment on both sides).
    """
    num_bin = go_left.shape[0]
    width = work.shape[2]
    if width % 128:
        raise ValueError(
            "fused partition needs width as whole 128-lane tiles, got %d"
            % width)
    sb = min(sb, ch)
    if ch % sb:
        raise ValueError("partition chunk %d must be a multiple of the "
                         "sub-block %d" % (ch, sb))
    scalars = jnp.concatenate([
        jnp.stack([src_plane.astype(jnp.int32), start.astype(jnp.int32),
                   cnt.astype(jnp.int32), feat.astype(jnp.int32)]),
        pack_table_bits(go_left)])

    kern = partial(_partition_kernel, ch=ch, sb=sb, width=width,
                   num_bin=num_bin)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((sb, sb), jnp.bfloat16),            # tril
            pltpu.VMEM((2, ch, width), jnp.uint8),         # cin x2
            pltpu.VMEM((2, ALIGN, width), jnp.uint8),      # edge prefills
            pltpu.VMEM((3 * ch, width), jnp.float32),      # lstage
            pltpu.VMEM((3 * ch, width), jnp.float32),      # rstage
            pltpu.VMEM((2, ch, width), jnp.uint8),         # lfb x2
            pltpu.VMEM((2, ch, width), jnp.uint8),         # rfb x2
            pltpu.SemaphoreType.DMA((8,)),
        ],
    )
    work_out, lt = pl.pallas_call(
        kern,
        name="partition_segment_fused",
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(scalars, work)
    return work_out, lt[0]


# ---------------------------------------------------------------------------
# Fused Pallas kernel, planes layout
# ---------------------------------------------------------------------------
#
# Transposed twin of _partition_kernel. All DYNAMIC offsets live on the
# LANE dim (rows of data are lanes), where Mosaic's tiling is strictest —
# so the kernel never slices VMEM dynamically on lanes at all:
#
# - HBM chunk reads use 128-lane-aligned windows derived as (x//128)*128
#   at every use site (the lane twin of the rows kernel's (x//32)*32);
# - in-chunk compaction runs per SB-column sub-block as ONE perm matmul
#   (SB, LCAP) that does placement AND the circular wrap arithmetically:
#   dest = (cursor + rank) mod LCAP, so frontier rows land at absolute
#   circular stage slots and the stage update is a full-stage ADD — no
#   dynamic window, no roll;
# - the circular stages are (W, LCAP=2*SB) f32; a flush converts one
#   STATIC half to u8 and pure-writes it to an aligned HBM window, then
#   zeroes the half (future adds land on zeros);
# - leftovers drain as up to 2 serial RMW tiles per side, left fully
#   before right (their windows can overlap in the middle of the segment).
#
# dst-plane state (edge prefills, drain RMW reads) is read through
# work_ref — the ALIASED OUTPUT — which is the same HBM buffer on device
# and keeps the kernel bit-faithful under the pallas interpreter, so the
# CPU suite validates it end-to-end (tests/test_work_layout.py). Per-row
# cost vs the rows kernel at W=64: DMA bytes ~2x lower, VPU converts ~2-3x
# lower, perm-matmul MACs comparable (2*W*LCAP vs 2*(SB+8)*128) — the
# expected win is the DMA/VPU term (PERF.md layout row; on-TPU A/B via
# scripts/layout_bisect.py).


def _partition_planes_kernel(sref, work_in, work_ref, lt_ref,
                             triu, cin, pre, lstage, rstage, lfb, rfb, sem,
                             *, ch, sb, nplanes):
    f32 = jnp.float32
    lcap = 2 * sb
    nsub = ch // sb
    W = nplanes
    src_plane = sref[0]
    start = sref[1]
    cnt = sref[2]
    feat = sref[3]
    dst_plane = 1 - src_plane

    def a128(x):
        # lane twin of the rows kernel's a32: re-derive every HBM lane
        # offset as (x // 128) * 128 at the use site so Mosaic can PROVE
        # whole-tile alignment
        return (x // PLANE_ALIGN) * PLANE_ALIGN

    lbase0 = (start // PLANE_ALIGN) * PLANE_ALIGN
    head_l = start - lbase0                  # 0..127 neighbor lanes below
    end = start + cnt
    rtop = ((end - 1) // PLANE_ALIGN) * PLANE_ALIGN
    rbase0 = rtop + PLANE_ALIGN
    tail_r = rbase0 - end                    # 0..127 neighbor lanes above

    tot = head_l + cnt
    nchunks = (tot + ch - 1) // ch

    # strict upper-triangular ones: ranks[j] = sum_{i<j} flags[i], flags
    # along the LANE dim (flags (2, SB) @ triu (SB, SB) -> (2, SB))
    row_i = jax.lax.broadcasted_iota(jnp.int32, (sb, sb), 0)
    col_i = jax.lax.broadcasted_iota(jnp.int32, (sb, sb), 1)
    triu[:] = jnp.clip(col_i - row_i, 0, 1).astype(f32).astype(jnp.bfloat16)

    lane_c = jax.lax.broadcasted_iota(jnp.int32, (1, ch), 1)
    sub_w = jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0)
    lane_128 = jax.lax.broadcasted_iota(jnp.int32, (W, PLANE_ALIGN), 1)
    lane_sb_w = jax.lax.broadcasted_iota(jnp.int32, (W, sb), 1)

    # ---- prefills: neighbor lanes of the aligned edge tiles ----
    pl_in = pltpu.make_async_copy(
        work_ref.at[dst_plane, :, pl.ds(lbase0, PLANE_ALIGN)],
        pre.at[0], sem.at[2])
    pl_in.start()
    pr_in = pltpu.make_async_copy(
        work_ref.at[dst_plane, :, pl.ds(rtop, PLANE_ALIGN)],
        pre.at[1], sem.at[3])
    pr_in.start()

    def start_in(i, slot):
        pltpu.make_async_copy(
            work_in.at[src_plane, :, pl.ds(a128(lbase0 + i * ch), ch)],
            cin.at[slot], sem.at[slot]).start()

    start_in(0, 0)

    # left stage: logical lane q (from lbase0, ascending) at slot q % LCAP.
    # right stage: descending index q (from rbase0) at slot LCAP-1-(q%LCAP)
    # — chosen so every flush half maps to its HBM window IN ORDER.
    lstage[...] = jnp.zeros((W, lcap), f32)
    rstage[...] = jnp.zeros((W, lcap), f32)
    pl_in.wait()
    lstage[:, 0:PLANE_ALIGN] = jnp.where(
        lane_128 < head_l, pre[0].astype(jnp.int32).astype(f32), 0.0)
    pr_in.wait()
    rstage[:, lcap - PLANE_ALIGN:lcap] = jnp.where(
        lane_128 >= PLANE_ALIGN - tail_r,
        pre[1].astype(jnp.int32).astype(f32), 0.0)

    def stage_half(stage, h):
        """STATIC half selected by a traced bit (no dynamic lane slicing)."""
        return jnp.where(h == 1, stage[:, sb:lcap], stage[:, 0:sb])

    def flush(stage, fb, flushed, left, sem_base):
        """Convert the completed SB-lane half, zero it, start its pure
        aligned HBM write."""
        nflush = flushed // sb
        slot = jax.lax.rem(nflush, 2)

        # slot reuse: wait the DMA issued 2 flushes ago (size-matched
        # reconstruction; .wait() only consumes the semaphore)
        @pl.when(nflush >= 2)
        def _():
            pltpu.make_async_copy(
                fb.at[slot], work_ref.at[dst_plane, :, pl.ds(0, sb)],
                sem.at[sem_base + slot]).wait()
        h = slot if left else 1 - slot
        lo_half = stage[:, 0:sb]
        hi_half = stage[:, sb:lcap]
        hb = h == 1
        fb[slot] = jnp.where(hb, hi_half, lo_half) \
            .astype(jnp.int32).astype(jnp.uint8)
        stage[:, 0:sb] = jnp.where(hb, lo_half, 0.0)
        stage[:, sb:lcap] = jnp.where(hb, 0.0, hi_half)
        if left:
            at = a128(lbase0 + flushed)
        else:
            at = a128(rbase0 - flushed) - sb
        pltpu.make_async_copy(
            fb.at[slot], work_ref.at[dst_plane, :, pl.ds(at, sb)],
            sem.at[sem_base + slot]).start()

    def body(i, carry):
        p_l, p_r, fl_l, fl_r = carry
        slot = jax.lax.rem(i, 2)
        pltpu.make_async_copy(
            work_in.at[src_plane, :, pl.ds(a128(lbase0 + i * ch), ch)],
            cin.at[slot], sem.at[slot]).wait()

        @pl.when(i + 1 < nchunks)
        def _():
            start_in(i + 1, 1 - slot)

        cf = cin[slot].astype(jnp.int32).astype(f32)          # (W, CH)
        # split column: one sublane reduction (feat is a traced sublane
        # index — never a dynamic VMEM slice)
        col = jnp.sum(jnp.where(sub_w == feat, cf, 0.0), axis=0,
                      keepdims=True)                          # (1, CH)
        coli = col.astype(jnp.int32)
        word = jax.lax.shift_right_logical(coli, 5)
        wvals = jnp.zeros((1, ch), jnp.int32)
        for w_ in range(TABLE_WORDS):
            wvals = jnp.where(word == w_, sref[4 + w_], wvals)
        bit = jnp.bitwise_and(coli, 31)
        go = jnp.bitwise_and(
            jax.lax.shift_right_logical(wvals, bit), 1) > 0
        pos = lane_c + i * ch
        valid = (pos >= head_l) & (pos < tot)                 # (1, CH)

        for s in range(nsub):
            sub = cf[:, s * sb:(s + 1) * sb]                  # (W, SB)
            gl = go[:, s * sb:(s + 1) * sb] & valid[:, s * sb:(s + 1) * sb]
            gr = (~go[:, s * sb:(s + 1) * sb]) & valid[:, s * sb:(s + 1) * sb]
            flags = jnp.concatenate(
                [gl.astype(jnp.bfloat16), gr.astype(jnp.bfloat16)], axis=0)
            ranks = jax.lax.dot(flags, triu[:],
                                preferred_element_type=f32)   # (2, SB)
            nl = jnp.sum(gl.astype(jnp.int32))
            nr = jnp.sum(gr.astype(jnp.int32))
            lrank = ranks[0:1, :].astype(jnp.int32)
            rrank = ranks[1:2, :].astype(jnp.int32)
            # absolute circular stage slots: the perm matmul does placement
            # AND the wrap; unrouted columns get -1 (all-zero perm column)
            dest_l = jnp.where(gl, jax.lax.rem(p_l + lrank, lcap), -1)
            dest_r = jnp.where(gr, lcap - 1 - jax.lax.rem(p_r + rrank, lcap),
                               -1)
            j_i = jax.lax.broadcasted_iota(jnp.int32, (sb, lcap), 1)
            perm_l = (1 - jnp.clip(jnp.abs(j_i - dest_l.reshape(sb, 1)),
                                   0, 1)).astype(f32).astype(jnp.bfloat16)
            perm_r = (1 - jnp.clip(jnp.abs(j_i - dest_r.reshape(sb, 1)),
                                   0, 1)).astype(f32).astype(jnp.bfloat16)
            # u8 payload bytes are integers <= 255: exact under a 0/1 bf16
            # permutation matmul with f32 accumulation
            sub_bf = sub.astype(jnp.bfloat16)
            out_l = jax.lax.dot(sub_bf, perm_l, preferred_element_type=f32)
            out_r = jax.lax.dot(sub_bf, perm_r, preferred_element_type=f32)
            lstage[...] += out_l
            rstage[...] += out_r
            p_l = p_l + nl
            p_r = p_r + nr

            @pl.when(p_l - fl_l >= sb)
            def _():
                flush(lstage, lfb, fl_l, True, 4)
            fl_l = jnp.where(p_l - fl_l >= sb, fl_l + sb, fl_l)

            @pl.when(p_r - fl_r >= sb)
            def _():
                flush(rstage, rfb, fl_r, False, 6)
            fl_r = jnp.where(p_r - fl_r >= sb, fl_r + sb, fl_r)

        return p_l, p_r, fl_l, fl_r

    p_l, p_r, fl_l, fl_r = jax.lax.fori_loop(
        0, nchunks, body,
        (head_l, tail_r, jnp.int32(0), jnp.int32(0)))

    # ---- drain: wait ALL outstanding flushes first (their tiles can sit
    # inside the other side's drain windows), then up to 2 serial RMW
    # tiles per side, LEFT fully before RIGHT (windows may overlap where
    # the frontiers meet) ----
    for base, fb, fl in ((4, lfb, fl_l), (6, rfb, fl_r)):
        nf = fl // sb
        for back in (1, 2):
            @pl.when(nf >= back)
            def _(base=base, fb=fb, nf=nf, back=back):
                pltpu.make_async_copy(
                    fb.at[jax.lax.rem(nf - back, 2)],
                    work_ref.at[dst_plane, :, pl.ds(0, sb)],
                    sem.at[base + jax.lax.rem(nf - back, 2)]).wait()

    for t in (0, 1):
        @pl.when(t * sb < p_l - fl_l)
        def _(t=t):
            at = a128(lbase0 + fl_l) + t * sb
            rd = pltpu.make_async_copy(
                work_ref.at[dst_plane, :, pl.ds(at, sb)], lfb.at[0],
                sem.at[4])
            rd.start()
            rd.wait()
            h = jax.lax.rem(fl_l // sb + t, 2)
            fresh = stage_half(lstage, h)
            old = lfb[0].astype(jnp.int32).astype(f32)
            qpos = fl_l + t * sb + lane_sb_w
            merged = jnp.where(qpos < p_l, fresh, old)
            lfb[0] = merged.astype(jnp.int32).astype(jnp.uint8)
            wr = pltpu.make_async_copy(
                lfb.at[0], work_ref.at[dst_plane, :, pl.ds(at, sb)],
                sem.at[4])
            wr.start()
            wr.wait()

    for t in (0, 1):
        @pl.when(t * sb < p_r - fl_r)
        def _(t=t):
            at = a128(rbase0 - fl_r) - (t + 1) * sb
            rd = pltpu.make_async_copy(
                work_ref.at[dst_plane, :, pl.ds(at, sb)], rfb.at[0],
                sem.at[6])
            rd.start()
            rd.wait()
            h = 1 - jax.lax.rem(fl_r // sb + t, 2)
            fresh = stage_half(rstage, h)
            old = rfb[0].astype(jnp.int32).astype(f32)
            # window lane c holds descending index q = fl_r+(t+1)*sb-1-c
            keep = lane_sb_w >= (t + 1) * sb - (p_r - fl_r)
            merged = jnp.where(keep, fresh, old)
            rfb[0] = merged.astype(jnp.int32).astype(jnp.uint8)
            wr = pltpu.make_async_copy(
                rfb.at[0], work_ref.at[dst_plane, :, pl.ds(at, sb)],
                sem.at[6])
            wr.start()
            wr.wait()

    lt_ref[0] = p_l - head_l


def partition_segment_planes_fused(
    work: jax.Array,       # (2, W, Npad) u8 ping-pong plane pair
    src_plane: jax.Array,
    start: jax.Array,
    cnt: jax.Array,
    feat: jax.Array,
    go_left: jax.Array,    # (B,) bool
    *,
    ch: int = DEFAULT_CH,
    sb: int = 256,
) -> Tuple[jax.Array, jax.Array]:
    """Pallas form of :func:`partition_segment_planes` (same contract,
    except row order WITHIN each side is unspecified — histograms are
    order-free and sub-splits re-partition).

    Requires whole-tile dims: Npad % 128 == 0 (lane DMA windows), plane
    count a multiple of 32 (u8 sublane tiles), ch a multiple of 128 and of
    the sub-block, and guards of at least ch + 2*PLANE_ALIGN lanes
    (work_spec/planes_npad provide all four).
    """
    num_bin = go_left.shape[0]
    _, nplanes, npad = work.shape
    if npad % 128:
        raise ValueError(
            "fused planes partition needs whole 128-lane tiles in the lane "
            "dim, got Npad=%d" % npad)
    if nplanes % 32:
        raise ValueError(
            "fused planes partition needs whole 32-sublane u8 tiles, got "
            "W=%d planes" % nplanes)
    sb = min(sb, ch)
    if ch % sb or ch % 128:
        raise ValueError(
            "planes partition chunk %d must be a multiple of 128 and of "
            "the sub-block %d" % (ch, sb))
    scalars = jnp.concatenate([
        jnp.stack([src_plane.astype(jnp.int32), start.astype(jnp.int32),
                   cnt.astype(jnp.int32), feat.astype(jnp.int32)]),
        pack_table_bits(go_left)])

    kern = partial(_partition_planes_kernel, ch=ch, sb=sb, nplanes=nplanes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((sb, sb), jnp.bfloat16),              # triu
            pltpu.VMEM((2, nplanes, ch), jnp.uint8),         # cin x2
            pltpu.VMEM((2, nplanes, PLANE_ALIGN), jnp.uint8),  # prefills
            pltpu.VMEM((nplanes, 2 * sb), jnp.float32),      # lstage
            pltpu.VMEM((nplanes, 2 * sb), jnp.float32),      # rstage
            pltpu.VMEM((2, nplanes, sb), jnp.uint8),         # lfb x2
            pltpu.VMEM((2, nplanes, sb), jnp.uint8),         # rfb x2
            pltpu.SemaphoreType.DMA((8,)),
        ],
    )
    work_out, lt = pl.pallas_call(
        kern,
        name="partition_segment_planes_fused",
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(scalars, work)
    return work_out, lt[0]


# ---------------------------------------------------------------------------
# One-kernel split: partition + smaller-child histogram + split scan
# ---------------------------------------------------------------------------
#
# The fused planes path pays THREE device launches per split — partition,
# smaller-child histogram, split scan — and the histogram launch re-reads
# the freshly routed child rows from HBM. This kernel runs all three as
# sequential phases of ONE pallas_call (tpu_split_kernel=on):
#
#   A. partition — _partition_planes_kernel called as a plain function on
#      the same refs/scratch; bytes land in work_ref (the aliased output)
#      exactly as the standalone launch leaves them.
#   B. smaller-child histogram — re-streams the routed child's contiguous
#      segment from work_ref through 128-lane-aligned DMA windows of
#      hist_chunk + 128 lanes, then slices the oracle's UNALIGNED chunk out
#      in VMEM. The chunk grid, valid masking and _hist16_chunk_planes f32
#      accumulation order replicate hist16_segment_planes /
#      hist16_segment_resident byte-for-byte — bit-identity with the
#      three-launch oracle is the contract, which is also why the child
#      bytes are still READ once here (accumulating during routing would
#      change the f32 chunk grouping): the launch disappears, the re-read
#      stays (PERF.md round 12 is honest about this).
#   C. scan tail — sibling histogram by parent-minus-child subtraction,
#      then find_best_split vmapped over both children on the SAME inputs
#      the learner's node_best_pair would see; SplitInfo fields write to
#      dedicated outputs.
#
# Validation status: bit-parity is proven under the pallas interpreter
# (tests/test_one_kernel.py grows bit-identical trees vs the oracle). On
# real Mosaic the scan tail (argsort/switch in find_best_split) and the
# resident gather do not lower yet — tpu_split_kernel=auto therefore
# resolves to "off" everywhere and the first v5e session A/Bs it via
# scripts/split_bisect.py. Phases A/B are written DMA-aligned so that
# bring-up starts from a TPU-shaped kernel.


def _one_kernel_split_kernel(sref, *refs, ch, sb, nplanes, hist_ch,
                             num_feat, num_bins, exact, lo_w, hp, resident,
                             npad):
    from .split import FeatureMeta, find_best_split

    f32 = jnp.float32
    base = 2 if resident else 1
    work_in = refs[0]
    res_in = refs[1] if resident else None
    (phist_in, nb_in, mm_in, mb_in, ic_in, mono_in, pen_in, cegb_in,
     fmask_in, sums_in, outs_in, lows_in, ups_in) = refs[base:base + 13]
    (work_ref, lt_ref, hl_ref, hr_ref, g_ref, f_ref, b_ref, k_ref, dl_ref,
     gl_ref, lsum_ref, rsum_ref, lout_ref, rout_ref) = \
        refs[base + 13:base + 27]
    (triu, cin, pre, lstage, rstage, lfb, rfb, sem, hbuf) = refs[base + 27:]

    # ---- phase A: partition (identical code, identical bytes) ----
    _partition_planes_kernel(sref, work_in, work_ref, lt_ref, triu, cin,
                             pre, lstage, rstage, lfb, rfb, sem,
                             ch=ch, sb=sb, nplanes=nplanes)

    # ---- phase B: smaller-child histogram over the routed segment ----
    from .histogram import _hist16_chunk_planes, _hist16_combine

    start = sref[1]
    cnt = sref[2]
    dst = 1 - sref[0]
    lt = lt_ref[0]
    left_smaller = sref[12] == 1
    small_start = jnp.where(left_smaller, start, start + lt)
    small_cnt = jnp.where(left_smaller, lt, cnt - lt)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    nchunks = (small_cnt + hist_ch - 1) // hist_ch
    res = res_in[...] if resident else None

    def hbody(i, acc):
        off = small_start + i * hist_ch
        # Mosaic wants provably 128-lane-aligned HBM offsets: DMA the
        # aligned superset window, slice the oracle's unaligned chunk out
        # in VMEM (guard >= hist_chunk + 2*PLANE_ALIGN keeps it in bounds)
        at = (off // PLANE_ALIGN) * PLANE_ALIGN
        cp = pltpu.make_async_copy(
            work_ref.at[dst, :, pl.ds(at, hist_ch + PLANE_ALIGN)],
            hbuf, sem.at[0])
        cp.start()
        cp.wait()
        cw = jax.lax.dynamic_slice(hbuf[...], (jnp.int32(0), off - at),
                                   (nplanes, hist_ch))
        if resident:
            ridx = _decode_ridx(cw[RST_ROUTE:RST_GH_OFF], npad)
            cb = jnp.take(res, ridx, axis=1)              # (F, CH)
            cg = unpack_ghc_planes(cw, RST_GH_OFF)        # (3, CH)
        else:
            cb = cw[:num_feat]
            cg = unpack_ghc_planes(cw, num_feat)
        rows_left = small_cnt - i * hist_ch
        valid = jnp.arange(hist_ch, dtype=jnp.int32) < rows_left
        cgm = cg * valid[None, :].astype(f32)
        return acc + _hist16_chunk_planes(cb, cgm, num_bins, exact, lo_w)

    acc = jax.lax.fori_loop(
        0, nchunks, hbody,
        jnp.zeros((num_feat, sh, lo_w * nch), f32))
    hist_small = _hist16_combine(acc, num_bins, exact, lo_w)  # (F, B, 3)

    # ---- phase C: sibling by subtraction + fused split scan ----
    parent_hist = phist_in[...]
    hist_large = parent_hist - hist_small
    hist_left = jnp.where(left_smaller, hist_small, hist_large)
    hist_right = jnp.where(left_smaller, hist_large, hist_small)
    hl_ref[...] = hist_left
    hr_ref[...] = hist_right

    meta = FeatureMeta(
        num_bins=nb_in[...], movable_missing=mm_in[...],
        missing_bin=mb_in[...], is_categorical=ic_in[...],
        monotone=mono_in[...], penalty=pen_in[...],
        cegb_coupled=cegb_in[...])
    fmask = fmask_in[...]
    depth = sref[13]

    # the learner's node_best_pair reduces to exactly this under the
    # one-kernel eligibility gate (serial comm, no bundling/CEGB/by-node
    # RNG): find_best_split vmapped over the two children
    infos = jax.vmap(
        lambda hg, tg, po, lo, up: find_best_split(
            hg, tg, meta, fmask, hp, parent_output=po, leaf_lower=lo,
            leaf_upper=up, node_depth=depth)
    )(jnp.stack([hist_left, hist_right]), sums_in[...], outs_in[...],
      lows_in[...], ups_in[...])
    g_ref[...] = infos.gain.astype(f32)
    f_ref[...] = infos.feature.astype(jnp.int32)
    b_ref[...] = infos.bin.astype(jnp.int32)
    k_ref[...] = infos.kind.astype(jnp.int32)
    dl_ref[...] = infos.default_left.astype(jnp.bool_)
    gl_ref[...] = infos.go_left.astype(jnp.bool_)
    lsum_ref[...] = infos.left_sum.astype(f32)
    rsum_ref[...] = infos.right_sum.astype(f32)
    lout_ref[...] = infos.left_output.astype(f32)
    rout_ref[...] = infos.right_output.astype(f32)


def one_kernel_split_planes(
    work: jax.Array,        # (2, W, Npad) u8 ping-pong plane pair
    src_plane: jax.Array,
    start: jax.Array,
    cnt: jax.Array,
    feat: jax.Array,        # routed plane index (0 for resident)
    go_left: jax.Array,     # (B,) bool routing table
    left_smaller: jax.Array,  # scalar bool: left child is the smaller one
    depth: jax.Array,       # scalar i32 child depth (node_depth of the scan)
    parent_hist: jax.Array,  # (F, B, 3) f32 parent histogram
    meta,                   # FeatureMeta of (F,) arrays
    fmask: jax.Array,       # (F,) bool search mask
    sums2: jax.Array,       # (2, 3) f32 [left_sum, right_sum]
    outs2: jax.Array,       # (2,) f32 child outputs
    lows2: jax.Array,       # (2,) f32 child lower bounds
    ups2: jax.Array,        # (2,) f32 child upper bounds
    hp,                     # SplitHyper (static python scalars)
    *,
    num_bins: int,
    num_feat: int,
    exact: bool = True,
    ch: int = DEFAULT_CH,
    sb: int = 256,
    hist_chunk: int = 2048,
    lo_w: int = 0,
    resident_planes: jax.Array = None,  # (F, Npad) u8 resident bin planes
):
    """ONE pallas launch per split: partition + smaller-child histogram +
    split scan (see the module comment above). Same partition contract as
    :func:`partition_segment_planes_fused`; histogram and SplitInfo values
    are bit-identical to the three-launch chain it replaces.

    Returns ``(work, lt, hist_left, hist_right, infos)`` where ``infos`` is
    a batch-2 SplitInfo (left child then right child).
    """
    from .histogram import auto_lo_w
    from .split import SplitInfo

    _, nplanes, npad = work.shape
    if npad % 128:
        raise ValueError(
            "one-kernel split needs whole 128-lane tiles in the lane dim, "
            "got Npad=%d" % npad)
    if nplanes % 32:
        raise ValueError(
            "one-kernel split needs whole 32-sublane u8 tiles, got W=%d "
            "planes" % nplanes)
    sb = min(sb, ch)
    if ch % sb or ch % 128:
        raise ValueError(
            "one-kernel split chunk %d must be a multiple of 128 and of "
            "the sub-block %d" % (ch, sb))
    if hist_chunk % 128:
        # the in-kernel histogram DMA re-derives lane offsets as
        # (x // 128) * 128; a misaligned chunk would shift the VMEM slice
        raise ValueError(
            "one-kernel split hist_chunk must be a multiple of 128, got %d"
            % hist_chunk)
    resident = resident_planes is not None
    lo_w = lo_w or auto_lo_w(num_feat)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    f32 = jnp.float32
    i32 = jnp.int32

    scalars = jnp.concatenate([
        jnp.stack([src_plane.astype(i32), start.astype(i32),
                   cnt.astype(i32), feat.astype(i32)]),
        pack_table_bits(go_left),
        jnp.stack([left_smaller.astype(i32), depth.astype(i32)])])

    kern = partial(_one_kernel_split_kernel, ch=ch, sb=sb, nplanes=nplanes,
                   hist_ch=hist_chunk, num_feat=num_feat, num_bins=num_bins,
                   exact=exact, lo_w=lo_w, hp=hp, resident=resident,
                   npad=npad)
    extra_in = [resident_planes] if resident else []
    extra_in += [parent_hist, meta.num_bins, meta.movable_missing,
                 meta.missing_bin, meta.is_categorical, meta.monotone,
                 meta.penalty, meta.cegb_coupled, fmask, sums2, outs2,
                 lows2, ups2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)] * (1 + len(extra_in)),
        out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.HBM)] * 12,
        scratch_shapes=[
            pltpu.VMEM((sb, sb), jnp.bfloat16),                # triu
            pltpu.VMEM((2, nplanes, ch), jnp.uint8),           # cin x2
            pltpu.VMEM((2, nplanes, PLANE_ALIGN), jnp.uint8),  # prefills
            pltpu.VMEM((nplanes, 2 * sb), f32),                # lstage
            pltpu.VMEM((nplanes, 2 * sb), f32),                # rstage
            pltpu.VMEM((2, nplanes, sb), jnp.uint8),           # lfb x2
            pltpu.VMEM((2, nplanes, sb), jnp.uint8),           # rfb x2
            pltpu.SemaphoreType.DMA((8,)),
            pltpu.VMEM((nplanes, hist_chunk + PLANE_ALIGN),
                       jnp.uint8),                             # hist window
        ],
    )
    del sh, nch  # shapes below are post-combine; acc lives in the kernel
    B = num_bins
    F = num_feat
    outs = pl.pallas_call(
        kern,
        name="one_kernel_split_planes",
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(work.shape, work.dtype),
            jax.ShapeDtypeStruct((1,), i32),
            jax.ShapeDtypeStruct((F, B, 3), f32),   # hist_left
            jax.ShapeDtypeStruct((F, B, 3), f32),   # hist_right
            jax.ShapeDtypeStruct((2,), f32),        # gain
            jax.ShapeDtypeStruct((2,), i32),        # feature
            jax.ShapeDtypeStruct((2,), i32),        # bin
            jax.ShapeDtypeStruct((2,), i32),        # kind
            jax.ShapeDtypeStruct((2,), jnp.bool_),  # default_left
            jax.ShapeDtypeStruct((2, B), jnp.bool_),  # go_left
            jax.ShapeDtypeStruct((2, 3), f32),      # left_sum
            jax.ShapeDtypeStruct((2, 3), f32),      # right_sum
            jax.ShapeDtypeStruct((2,), f32),        # left_output
            jax.ShapeDtypeStruct((2,), f32),        # right_output
        ],
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(scalars, work, *extra_in)
    (work_out, lt, hist_left, hist_right, gain, feature, bin_, kind,
     default_left, go_left_out, left_sum, right_sum, left_output,
     right_output) = outs
    infos = SplitInfo(gain=gain, feature=feature, bin=bin_, kind=kind,
                      default_left=default_left, go_left=go_left_out,
                      left_sum=left_sum, right_sum=right_sum,
                      left_output=left_output, right_output=right_output)
    return work_out, lt[0], hist_left, hist_right, infos
