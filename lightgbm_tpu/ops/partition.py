"""Leaf-contiguous row partition, the device analog of DataPartition.

The reference keeps per-leaf row-index lists and stably partitions the
parent's indices on every split (reference: src/treelearner/
data_partition.hpp:101 Split, via ParallelPartitionRunner, threading.h:22).
That contract — per-split work proportional to the PARENT leaf, histograms
proportional to the CHILD leaf — is what makes 255-leaf trees affordable;
an O(N)-per-split design pays ~num_leaves/log(num_leaves) times more.

TPU-native form: rows are kept PHYSICALLY grouped by leaf in a packed
working buffer, so the histogram kernel streams a contiguous segment with
zero gathers (TPU row-gathers measured ~60ns/row — unusable; contiguous
dynamic slices run at HBM bandwidth). The working row layout is

    [ bins u8 x F | g f32 as 4 bytes | h f32 | cnt f32 ]   -> (Npad, F+12) u8

one array, one dtype: a split is ONE dynamic_slice per chunk, one in-chunk
compaction, two blended writes. f32 channels ride the compaction matmul as
their four u8 bytes — each byte is an integer <= 255, exactly representable
in bf16, so a 0/1 permutation matmul moves rows bit-exactly.

A split stably partitions the parent's segment [start, start+cnt):

- chunks of CH rows are compacted in-register via a (CH, CH) permutation
  one-hot matmul (MXU), left rows to the chunk front, right rows to the
  chunk back;
- compacted chunks are written with two cursors (left ascending from
  ``start``, right descending from ``start+cnt``) into the OTHER buffer of
  a ping-pong pair — children flip parity, nothing is copied back. Writes
  are blended read-modify-writes that touch only the valid rows, so the
  result is exact with no variable-length writes anywhere. The right
  child's rows land chunk-reversed — leaf row order is insignificant
  (histograms are order-free; sub-splits re-partition).

All ops are dynamic_slice / dynamic_update_slice / small matmuls — plain
XLA, so the same code runs on TPU, on the CPU test mesh, and inside
shard_map for the distributed learners.

Buffers carry a CH-row guard region at BOTH ends (rows live in
[GUARD, GUARD + n)) so slice windows never clamp.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

DEFAULT_CH = 2048
GH_BYTES = 12   # g, h, cnt as f32 bytes
GH_BYTES_Q = 3  # quantized: g, h as int8 bits, cnt as u8


def guard_rows(ch: int = DEFAULT_CH) -> int:
    return ch


def pack_rows(bins: jax.Array, ghc: jax.Array) -> jax.Array:
    """(N, F) u8 + (N, 3) f32 -> (N, F+12) u8 packed working rows."""
    gb = jax.lax.bitcast_convert_type(ghc.astype(jnp.float32), jnp.uint8)
    return jnp.concatenate([bins, gb.reshape(ghc.shape[0], GH_BYTES)], axis=1)


def unpack_ghc(rows: jax.Array, num_feat: int) -> jax.Array:
    """(N, F+12) u8 packed rows -> (N, 3) f32 channels."""
    gb = rows[:, num_feat:num_feat + GH_BYTES].reshape(rows.shape[0], 3, 4)
    return jax.lax.bitcast_convert_type(gb, jnp.float32)


def pack_rows_quantized(bins: jax.Array, ghc: jax.Array, key: jax.Array,
                        gscale, hscale) -> jax.Array:
    """(N, F) u8 + (N, 3) f32 -> (N, F+3) u8 with int8-quantized gradients.

    Stochastic rounding (floor(x*scale + u), u ~ U[0,1)) keeps histogram
    sums unbiased — the LightGBM quantized-training recipe (NeurIPS'22;
    LightGBM 4.x use_quantized_grad) at 8 bits instead of 2-5.
    """
    n = ghc.shape[0]
    u = jax.random.uniform(key, (n, 2))
    gq = jnp.clip(jnp.floor(ghc[:, 0] * gscale + u[:, 0]), -127, 127) \
        .astype(jnp.int8)
    hq = jnp.clip(jnp.floor(ghc[:, 1] * hscale + u[:, 1]), -127, 127) \
        .astype(jnp.int8)
    cnt = ghc[:, 2].astype(jnp.uint8)
    qb = jnp.stack([jax.lax.bitcast_convert_type(gq, jnp.uint8),
                    jax.lax.bitcast_convert_type(hq, jnp.uint8), cnt], axis=1)
    return jnp.concatenate([bins, qb], axis=1)


def unpack_ghq(rows: jax.Array, num_feat: int):
    """(N, F+3) u8 packed rows -> int8 g, int8 h, u8 cnt columns."""
    gq = jax.lax.bitcast_convert_type(rows[:, num_feat], jnp.int8)
    hq = jax.lax.bitcast_convert_type(rows[:, num_feat + 1], jnp.int8)
    return gq, hq, rows[:, num_feat + 2]


def _compact_chunk(cw, go, valid):
    """Stable in-chunk compaction: left rows to the front, right rows to the
    back, invalid (out-of-segment) rows parked in the middle gap.

    cw: (CH, W) u8 packed rows; go/valid: (CH,) bool.
    Returns (cw', nl, nr).
    """
    ch = cw.shape[0]
    gl = go & valid
    gr = (~go) & valid
    nl = jnp.sum(gl.astype(jnp.int32))
    nr = jnp.sum(gr.astype(jnp.int32))
    lrank = jnp.cumsum(gl.astype(jnp.int32)) - gl.astype(jnp.int32)
    rrank = jnp.cumsum(gr.astype(jnp.int32)) - gr.astype(jnp.int32)
    irank = jnp.cumsum((~valid).astype(jnp.int32)) - (~valid).astype(jnp.int32)
    dest = jnp.where(gl, lrank,
                     jnp.where(gr, ch - nr + rrank, nl + irank))
    # permutation one-hot: P[j, i] = (dest_i == j); compacted = P @ rows.
    # u8 payload bytes are integers <= 255: exact under a 0/1 bf16 matmul.
    iota = jnp.arange(ch, dtype=jnp.int32)
    perm = (dest[None, :] == iota[:, None]).astype(jnp.bfloat16)
    cw2 = jax.lax.dot(perm, cw.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    return cw2.astype(jnp.uint8), nl, nr


def partition_segment(
    work: jax.Array,     # (2, Npad, F+12) u8 ping-pong buffer pair
    src_plane: jax.Array,  # scalar i32 plane holding the parent's rows
    start: jax.Array,    # scalar i32 physical start (includes guard offset)
    cnt: jax.Array,      # scalar i32 physical rows in the segment
    feat: jax.Array,     # scalar i32 split feature
    go_left: jax.Array,  # (B,) bool bin routing table
    *,
    ch: int = DEFAULT_CH,
) -> Tuple[jax.Array, jax.Array]:
    """Stable-partition rows [start, start+cnt) of plane ``src_plane`` into
    plane ``1 - src_plane`` (children flip parity — the plane index is a
    traced scalar, so no lax.cond / buffer copy is ever needed).

    Returns (work, left_cnt): left child at [start, start+left_cnt),
    right child rows (unordered) at [start+left_cnt, start+cnt).
    """
    num_bin = go_left.shape[0]
    table = go_left.astype(jnp.float32)
    nchunks = (cnt + ch - 1) // ch
    width = work.shape[2]
    dst_plane = 1 - src_plane

    def body(i, carry):
        work, lcur, rcur = carry
        off = start + i * ch
        cw = jax.lax.dynamic_slice(work, (src_plane, off, 0),
                                   (1, ch, width))[0]
        col = jax.lax.dynamic_index_in_dim(cw, feat, axis=1,
                                           keepdims=False).astype(jnp.int32)
        # gather-free table lookup: one-hot contraction over the bin axis
        oh = (col[:, None] == jnp.arange(num_bin, dtype=jnp.int32)[None, :])
        go = (oh.astype(jnp.float32) @ table) > 0.5
        pos = off + jnp.arange(ch, dtype=jnp.int32)
        valid = pos < start + cnt
        cw2, nl, nr = _compact_chunk(cw, go, valid)

        # blended read-modify-writes touch only the valid rows: exact, no
        # branches (lax.cond here would force buffer copies and break XLA's
        # in-place aliasing of the fori carry)
        j = jnp.arange(ch, dtype=jnp.int32)[:, None]

        def blend_at(work, at, keep_left):
            cur = jax.lax.dynamic_slice(work, (dst_plane, at, 0),
                                        (1, ch, width))[0]
            m = (j < nl) if keep_left else (j >= ch - nr)
            return jax.lax.dynamic_update_slice(
                work, jnp.where(m, cw2, cur)[None], (dst_plane, at, 0))

        work = blend_at(work, lcur, True)
        work = blend_at(work, rcur - ch, False)
        return work, lcur + nl, rcur - nr

    work, lcur, _ = jax.lax.fori_loop(
        0, nchunks, body, (work, start, start + cnt))
    return work, lcur - start
