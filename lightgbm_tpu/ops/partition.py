"""Leaf-contiguous row partition, the device analog of DataPartition.

The reference keeps per-leaf row-index lists and stably partitions the
parent's indices on every split (reference: src/treelearner/
data_partition.hpp:101 Split, via ParallelPartitionRunner, threading.h:22).
That contract — per-split work proportional to the PARENT leaf, histograms
proportional to the CHILD leaf — is what makes 255-leaf trees affordable;
an O(N)-per-split design pays ~num_leaves/log(num_leaves) times more.

TPU-native form: rows are kept PHYSICALLY grouped by leaf in a packed
working buffer, so the histogram kernel streams a contiguous segment with
zero gathers (TPU row-gathers measured ~60ns/row — unusable; contiguous
dynamic slices run at HBM bandwidth). The working row layout is

    [ bins u8 x F | g f32 as 4 bytes | h f32 | cnt f32 ]   -> (Npad, F+12) u8

one array, one dtype: a split is ONE dynamic_slice per chunk, one in-chunk
compaction, two blended writes. f32 channels ride the compaction matmul as
their four u8 bytes — each byte is an integer <= 255, exactly representable
in bf16, so a 0/1 permutation matmul moves rows bit-exactly.

A split stably partitions the parent's segment [start, start+cnt):

- chunks of CH rows are compacted in-register via a (CH, CH) permutation
  one-hot matmul (MXU), left rows to the chunk front, right rows to the
  chunk back;
- compacted chunks are written with two cursors (left ascending from
  ``start``, right descending from ``start+cnt``) into the OTHER buffer of
  a ping-pong pair — children flip parity, nothing is copied back. Writes
  are blended read-modify-writes that touch only the valid rows, so the
  result is exact with no variable-length writes anywhere. The right
  child's rows land chunk-reversed — leaf row order is insignificant
  (histograms are order-free; sub-splits re-partition).

All ops are dynamic_slice / dynamic_update_slice / small matmuls — plain
XLA, so the same code runs on TPU, on the CPU test mesh, and inside
shard_map for the distributed learners.

Buffers carry a CH-row guard region at BOTH ends (rows live in
[GUARD, GUARD + n)) so slice windows never clamp.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

try:  # pallas is optional at import time (CPU test meshes use the XLA path)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = pltpu = None

DEFAULT_CH = 2048
GH_BYTES = 12   # g, h, cnt as f32 bytes
GH_BYTES_Q = 3  # quantized: g, h as int8 bits, cnt as u8


def guard_rows(ch: int = DEFAULT_CH) -> int:
    return ch


def pack_rows(bins: jax.Array, ghc: jax.Array) -> jax.Array:
    """(N, F) u8 + (N, 3) f32 -> (N, F+12) u8 packed working rows."""
    gb = jax.lax.bitcast_convert_type(ghc.astype(jnp.float32), jnp.uint8)
    return jnp.concatenate([bins, gb.reshape(ghc.shape[0], GH_BYTES)], axis=1)


def unpack_ghc(rows: jax.Array, num_feat: int) -> jax.Array:
    """(N, F+12) u8 packed rows -> (N, 3) f32 channels."""
    gb = rows[:, num_feat:num_feat + GH_BYTES].reshape(rows.shape[0], 3, 4)
    return jax.lax.bitcast_convert_type(gb, jnp.float32)


def pack_rows_quantized(bins: jax.Array, ghc: jax.Array, key: jax.Array,
                        gscale, hscale) -> jax.Array:
    """(N, F) u8 + (N, 3) f32 -> (N, F+3) u8 with int8-quantized gradients.

    Stochastic rounding (floor(x*scale + u), u ~ U[0,1)) keeps histogram
    sums unbiased — the LightGBM quantized-training recipe (NeurIPS'22;
    LightGBM 4.x use_quantized_grad) at 8 bits instead of 2-5.
    """
    n = ghc.shape[0]
    u = jax.random.uniform(key, (n, 2))
    gq = jnp.clip(jnp.floor(ghc[:, 0] * gscale + u[:, 0]), -127, 127) \
        .astype(jnp.int8)
    hq = jnp.clip(jnp.floor(ghc[:, 1] * hscale + u[:, 1]), -127, 127) \
        .astype(jnp.int8)
    cnt = ghc[:, 2].astype(jnp.uint8)
    qb = jnp.stack([jax.lax.bitcast_convert_type(gq, jnp.uint8),
                    jax.lax.bitcast_convert_type(hq, jnp.uint8), cnt], axis=1)
    return jnp.concatenate([bins, qb], axis=1)


def unpack_ghq(rows: jax.Array, num_feat: int):
    """(N, F+3) u8 packed rows -> int8 g, int8 h, u8 cnt columns."""
    gq = jax.lax.bitcast_convert_type(rows[:, num_feat], jnp.int8)
    hq = jax.lax.bitcast_convert_type(rows[:, num_feat + 1], jnp.int8)
    return gq, hq, rows[:, num_feat + 2]


def _compact_chunk(cw, go, valid):
    """Stable in-chunk compaction: left rows to the front, right rows to the
    back, invalid (out-of-segment) rows parked in the middle gap.

    cw: (CH, W) u8 packed rows; go/valid: (CH,) bool.
    Returns (cw', nl, nr).
    """
    ch = cw.shape[0]
    gl = go & valid
    gr = (~go) & valid
    # one fused (CH, 3) prefix scan instead of three (profiled: each scan
    # is a separate ~2 us reduce-window per chunk)
    flags = jnp.stack([gl, gr, ~valid], axis=1).astype(jnp.int32)
    ranks = jnp.cumsum(flags, axis=0) - flags
    lrank, rrank, irank = ranks[:, 0], ranks[:, 1], ranks[:, 2]
    nl = ranks[-1, 0] + flags[-1, 0]
    nr = ranks[-1, 1] + flags[-1, 1]
    dest = jnp.where(gl, lrank,
                     jnp.where(gr, ch - nr + rrank, nl + irank))
    # permutation one-hot: P[j, i] = (dest_i == j); compacted = P @ rows.
    # u8 payload bytes are integers <= 255: exact under a 0/1 bf16 matmul.
    iota = jnp.arange(ch, dtype=jnp.int32)
    perm = (dest[None, :] == iota[:, None]).astype(jnp.bfloat16)
    cw2 = jax.lax.dot(perm, cw.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    return cw2.astype(jnp.uint8), nl, nr


def partition_segment(
    work: jax.Array,     # (2, Npad, F+12) u8 ping-pong buffer pair
    src_plane: jax.Array,  # scalar i32 plane holding the parent's rows
    start: jax.Array,    # scalar i32 physical start (includes guard offset)
    cnt: jax.Array,      # scalar i32 physical rows in the segment
    feat: jax.Array,     # scalar i32 split feature
    go_left: jax.Array,  # (B,) bool bin routing table
    *,
    ch: int = DEFAULT_CH,
) -> Tuple[jax.Array, jax.Array]:
    """Stable-partition rows [start, start+cnt) of plane ``src_plane`` into
    plane ``1 - src_plane`` (children flip parity — the plane index is a
    traced scalar, so no lax.cond / buffer copy is ever needed).

    Returns (work, left_cnt): left child at [start, start+left_cnt),
    right child rows (unordered) at [start+left_cnt, start+cnt).
    """
    num_bin = go_left.shape[0]
    table = go_left.astype(jnp.float32)
    nchunks = (cnt + ch - 1) // ch
    width = work.shape[2]
    dst_plane = 1 - src_plane

    def body(i, carry):
        work, lcur, rcur = carry
        off = start + i * ch
        cw = jax.lax.dynamic_slice(work, (src_plane, off, 0),
                                   (1, ch, width))[0]
        col = jax.lax.dynamic_index_in_dim(cw, feat, axis=1,
                                           keepdims=False).astype(jnp.int32)
        # gather-free table lookup: one-hot contraction over the bin axis
        oh = (col[:, None] == jnp.arange(num_bin, dtype=jnp.int32)[None, :])
        go = (oh.astype(jnp.float32) @ table) > 0.5
        pos = off + jnp.arange(ch, dtype=jnp.int32)
        valid = pos < start + cnt
        cw2, nl, nr = _compact_chunk(cw, go, valid)

        # blended read-modify-writes touch only the valid rows: exact, no
        # branches (lax.cond here would force buffer copies and break XLA's
        # in-place aliasing of the fori carry)
        j = jnp.arange(ch, dtype=jnp.int32)[:, None]

        def blend_at(work, at, keep_left):
            cur = jax.lax.dynamic_slice(work, (dst_plane, at, 0),
                                        (1, ch, width))[0]
            m = (j < nl) if keep_left else (j >= ch - nr)
            return jax.lax.dynamic_update_slice(
                work, jnp.where(m, cw2, cur)[None], (dst_plane, at, 0))

        work = blend_at(work, lcur, True)
        work = blend_at(work, rcur - ch, False)
        return work, lcur + nl, rcur - nr

    work, lcur, _ = jax.lax.fori_loop(
        0, nchunks, body, (work, start, start + cnt))
    return work, lcur - start


# ---------------------------------------------------------------------------
# Fused Pallas kernel: the whole per-split pipeline in one device call
# ---------------------------------------------------------------------------
#
# partition_segment is ~10 XLA ops per chunk; at 2048-row chunks the fixed
# per-op cost (~19 us/chunk profiled) dominates the actual work (~4 us).
# A 255-leaf tree partitions ~5.6k chunks, so the op soup costs ~100 ms per
# tree at 2M rows — the single largest line in the round-2 profile. The
# Pallas version runs ONE kernel per split: an in-kernel chunk loop with
# manual HBM<->VMEM DMA, the same route/rank/permute math, and blended
# read-modify-write stores. Row ranks come from a strict-lower-triangular
# bf16 matmul (exact: 0/1 operands, f32 accumulation) instead of cumsum,
# and the compaction stays a permutation matmul on the MXU.


ALIGN = 32  # Mosaic requires u8 DMA row offsets provably 32-aligned


def work_spec(num_groups: int, quantized: bool, part_kernel: str,
              part_chunk: int, hist_chunk: int):
    """(guard_rows, row_width) of the packed ping-pong working buffer.

    Single source of truth shared by the tree builder and the fused
    trainer's carried-buffer allocation: the fused pallas kernel needs
    128-lane rows (whole-tile DMA) and guards that cover its aligned
    write windows reaching up to ALIGN rows past a segment on each side.
    """
    width = num_groups + (GH_BYTES_Q if quantized else GH_BYTES)
    guard = max(part_chunk, hist_chunk)
    if part_kernel == "pallas":
        width = 128 * ((width + 127) // 128)   # whole 128-lane DMA tiles
        guard += 2 * ALIGN
    return guard, width


def _partition_kernel(sref, work_in, table_ref, work_ref, lt_ref,
                      tril, cin, cw2p, lbuf, rbuf, sem, *, ch, width, num_bin):
    f32 = jnp.float32
    cho = ch + ALIGN
    src_plane = sref[0]
    start = sref[1]
    cnt = sref[2]
    feat = sref[3]
    dst_plane = 1 - src_plane
    # reads cover [astart, astart + nchunks*ch) with 32-aligned offsets;
    # the first `head` rows are masked invalid
    astart = (start // ALIGN) * ALIGN
    head = start - astart
    tot = head + cnt
    nchunks = (tot + ch - 1) // ch

    # strict lower-triangular ones: ranks[i] = sum_{j<i} flags[j].
    # Arithmetic construction (clamped integer difference) — boolean
    # (CH, CH) selects hit Mosaic relayout limits on i1 vectors.
    row_i = jax.lax.broadcasted_iota(jnp.int32, (ch, ch), 0)
    col_i = jax.lax.broadcasted_iota(jnp.int32, (ch, ch), 1)
    tril[:] = jnp.clip(row_i - col_i, 0, 1).astype(f32).astype(jnp.bfloat16)

    lane_w = jax.lax.broadcasted_iota(jnp.int32, (ch, width), 1)
    sub_i = jax.lax.broadcasted_iota(jnp.int32, (ch, 1), 0)
    sub_o = jax.lax.broadcasted_iota(jnp.int32, (cho, 1), 0)

    def start_in(i, slot):
        off = astart + i * ch
        pltpu.make_async_copy(
            work_in.at[src_plane, pl.ds(off, ch), :], cin.at[slot],
            sem.at[slot]).start()

    # double-buffered input: chunk i+1 streams in while i computes
    start_in(0, 0)

    def body(i, carry):
        lcur, rcur = carry
        slot = jax.lax.rem(i, 2)
        pltpu.make_async_copy(
            work_in.at[src_plane, pl.ds(astart + i * ch, ch), :],
            cin.at[slot], sem.at[slot]).wait()

        @pl.when(i + 1 < nchunks)
        def _():
            start_in(i + 1, 1 - slot)

        # the left read-modify window depends only on lcur: overlap its
        # read with the routing/compaction compute
        wl = (lcur // ALIGN) * ALIGN
        dl = lcur - wl
        lin = pltpu.make_async_copy(
            work_in.at[dst_plane, pl.ds(wl, cho), :], lbuf, sem.at[2])
        lin.start()

        # Mosaic has no direct u8<->f32 casts; bounce through i32
        cf = cin[slot].astype(jnp.int32).astype(f32)         # (CH, W)
        col = jnp.sum(jnp.where(lane_w == feat, cf, 0.0), axis=1,
                      keepdims=True)                         # (CH, 1) f32
        # routing table lookup as a one-hot contraction over the bin axis
        bin_l = jax.lax.broadcasted_iota(jnp.int32, (ch, num_bin), 1)
        oh = (1 - jnp.clip(jnp.abs(bin_l - col.astype(jnp.int32)), 0, 1)) \
            .astype(f32)
        go = jnp.sum(oh * table_ref[:], axis=1, keepdims=True) > 0.5
        pos = sub_i + i * ch
        valid = (pos >= head) & (pos < tot)                  # (CH, 1)
        gl = go & valid
        gr = (~go) & valid
        flags = jnp.concatenate(
            [gl.astype(jnp.bfloat16), gr.astype(jnp.bfloat16),
             (~valid).astype(jnp.bfloat16)], axis=1)         # (CH, 3)
        ranks = jax.lax.dot(tril[:], flags,
                            preferred_element_type=f32)      # (CH, 3)
        nl = jnp.sum(gl.astype(jnp.int32))
        nr = jnp.sum(gr.astype(jnp.int32))
        lrank = ranks[:, 0:1].astype(jnp.int32)
        rrank = ranks[:, 1:2].astype(jnp.int32)
        irank = ranks[:, 2:3].astype(jnp.int32)
        dest = jnp.where(gl, lrank,
                         jnp.where(gr, ch - nr + rrank, nl + irank))  # (CH,1)
        # permutation one-hot: perm[j, i] = (dest_i == j); compacted = P @ cw
        destT = dest.reshape(1, ch)
        perm = (1 - jnp.clip(
            jnp.abs(jax.lax.broadcasted_iota(jnp.int32, (ch, ch), 0) - destT),
            0, 1)).astype(f32).astype(jnp.bfloat16)
        # keep the compacted chunk in f32 (exact byte integers): Mosaic's
        # dynamic rotate has no i8 form
        cw2p[0:ch, :] = jax.lax.dot(perm, cf.astype(jnp.bfloat16),
                                    preferred_element_type=f32)

        # Writes go to 32-aligned windows of CHO = CH + 32 rows; cursor
        # misalignment is absorbed by a cyclic roll of the compacted chunk,
        # and blends keep only the landed rows.
        rolled_l = pltpu.roll(cw2p[:], dl, 0)
        lin.wait()
        lb = lbuf[:].astype(jnp.int32).astype(f32)
        lb = jnp.where((sub_o >= dl) & (sub_o < dl + nl), rolled_l, lb)
        lbuf[:] = lb.astype(jnp.int32).astype(jnp.uint8)
        lout = pltpu.make_async_copy(
            lbuf, work_ref.at[dst_plane, pl.ds(wl, cho), :], sem.at[2])
        lout.start()

        # right rows sit at [CH-nr, CH) in cw2p; land them at
        # [rcur-nr, rcur). The left write must complete first: the two
        # windows overlap when the cursors meet mid-segment.
        rstart = rcur - nr
        wr = (rstart // ALIGN) * ALIGN
        dr = rstart - wr
        shift_r = jnp.remainder(dr - (ch - nr), cho)
        rolled_r = pltpu.roll(cw2p[:], shift_r, 0)
        lout.wait()
        rin = pltpu.make_async_copy(
            work_in.at[dst_plane, pl.ds(wr, cho), :], rbuf, sem.at[3])
        rin.start()
        rin.wait()
        rb = rbuf[:].astype(jnp.int32).astype(f32)
        rb = jnp.where((sub_o >= dr) & (sub_o < dr + nr), rolled_r, rb)
        rbuf[:] = rb.astype(jnp.int32).astype(jnp.uint8)
        rout = pltpu.make_async_copy(
            rbuf, work_ref.at[dst_plane, pl.ds(wr, cho), :], sem.at[3])
        rout.start()
        rout.wait()
        return lcur + nl, rcur - nr

    lcur, _ = jax.lax.fori_loop(0, nchunks, body, (start, start + cnt))
    lt_ref[0] = lcur - start


def partition_segment_fused(
    work: jax.Array,       # (2, Npad, W) u8 ping-pong buffer pair
    src_plane: jax.Array,
    start: jax.Array,
    cnt: jax.Array,
    feat: jax.Array,
    go_left: jax.Array,    # (B,) bool
    *,
    ch: int = DEFAULT_CH,
) -> Tuple[jax.Array, jax.Array]:
    """Pallas form of :func:`partition_segment` (same contract).

    Requires the work buffer's row width padded to 128 (DMA slices must
    cover whole 128-lane tiles) and guard regions of at least ch + 32 rows
    (write windows extend up to 32 rows past the segment on both sides).
    """
    num_bin = go_left.shape[0]
    width = work.shape[2]
    if width % 128:
        raise ValueError(
            "fused partition needs width as whole 128-lane tiles, got %d"
            % width)
    scalars = jnp.stack([src_plane.astype(jnp.int32), start.astype(jnp.int32),
                         cnt.astype(jnp.int32), feat.astype(jnp.int32)])
    table = go_left.astype(jnp.float32).reshape(1, num_bin)

    kern = partial(_partition_kernel, ch=ch, width=width, num_bin=num_bin)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((ch, ch), jnp.bfloat16),        # tril
            pltpu.VMEM((2, ch, width), jnp.uint8),     # cin x2
            pltpu.VMEM((ch + ALIGN, width), jnp.float32),  # cw2p
            pltpu.VMEM((ch + ALIGN, width), jnp.uint8),  # lbuf
            pltpu.VMEM((ch + ALIGN, width), jnp.uint8),  # rbuf
            pltpu.SemaphoreType.DMA((4,)),
        ],
    )
    work_out, lt = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=64 * 1024 * 1024),
    )(scalars, work, table)
    return work_out, lt[0]
