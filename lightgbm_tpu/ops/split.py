"""Vectorized best-split search over histograms.

TPU-native replacement of the reference per-feature sequential threshold scan
(reference: src/treelearner/feature_histogram.hpp:858
FindBestThresholdSequentially, :278 FindBestThresholdCategoricalInner). Instead
of a bidirectional pointer walk per feature, the whole ``(features, bins)``
plane is scanned at once with prefix sums; missing-value direction is handled
by evaluating both default-left and default-right assignments; categorical
splits use a one-vs-rest scan (<= max_cat_to_onehot categories) or a
sorted-by-(grad/hess) many-vs-many prefix scan via ``argsort`` over the bin
axis. Everything is shape-static and jit/shard_map friendly.

Split-gain semantics mirror feature_histogram.hpp GetSplitGains /
CalculateSplittedLeafOutput: L1 thresholding, L2, max_delta_step clipping,
path smoothing, and basic monotone-constraint clamping; counts come from the
histogram's dedicated count channel (instead of the reference's
hessian-derived cnt_factor trick, feature_histogram.hpp:316).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

NEG_INF = -jnp.inf
K_EPSILON = 1e-15
# numerical split kinds
KIND_NUMERICAL = 0
KIND_CAT_ONEHOT = 1
KIND_CAT_MVM_ASC = 2
KIND_CAT_MVM_DESC = 3


class FeatureMeta(NamedTuple):
    """Per-feature static metadata as device arrays (F,)."""
    num_bins: jax.Array        # int32 total bins incl. missing bin
    movable_missing: jax.Array # bool: feature has a bin routed with the
                               # missing direction (NaN bin for MISSING_NAN,
                               # zero/default bin for MISSING_ZERO)
    missing_bin: jax.Array     # int32 index of the NaN bin (num_bins-1) or 0
    is_categorical: jax.Array  # bool
    monotone: jax.Array        # int8 in {-1, 0, +1}
    penalty: jax.Array         # float32 split-gain multiplier (feature_contri)
    cegb_coupled: jax.Array    # float32 per-feature coupled CEGB penalty


class SplitHyper(NamedTuple):
    """Static hyperparameters closed over at trace time
    (reference: the Config fields read by FeatureHistogram)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: float = 100.0
    path_smooth: float = 0.0
    has_categorical: bool = False
    has_monotone: bool = False
    # monotone constraint propagation method: basic bounds children by the
    # split midpoint; intermediate by the sibling's output
    # (reference: monotone_constraints.hpp:327 Basic, :463 Intermediate)
    mono_intermediate: bool = False
    # advanced: per-threshold piecewise bounds per (leaf, feature) with an
    # all-leaf refresh at every commit (reference: AdvancedLeafConstraints,
    # monotone_constraints.hpp:856 — reformulated as dense (L, F, B) bound
    # arrays + (L, F) bin-range boxes instead of pointer-walking)
    mono_advanced: bool = False
    # gain multiplier for splits on monotone features, decaying with leaf
    # depth (reference: monotone_constraints.hpp:355
    # ComputeMonotoneSplitGainPenalty)
    monotone_penalty: float = 0.0
    # CEGB (reference: cost_effective_gradient_boosting.hpp:66 DetlaGain)
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    use_cegb: bool = False


class SplitInfo(NamedTuple):
    """Best split for one leaf — fixed-shape device pytree
    (reference analog: src/treelearner/split_info.hpp SplitInfo)."""
    gain: jax.Array          # scalar f32; -inf when no valid split
    feature: jax.Array       # scalar i32 inner feature index
    bin: jax.Array           # scalar i32: threshold bin / category / prefix len
    kind: jax.Array          # scalar i32 KIND_*
    default_left: jax.Array  # scalar bool
    go_left: jax.Array       # (B,) bool bin routing table
    left_sum: jax.Array      # (3,) g,h,cnt
    right_sum: jax.Array     # (3,)
    left_output: jax.Array   # scalar f32
    right_output: jax.Array  # scalar f32


def _threshold_l1(g: jax.Array, l1: float) -> jax.Array:
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def calc_leaf_output(g, h, hp: SplitHyper, extra_l2: float = 0.0):
    """CalculateSplittedLeafOutput (feature_histogram.hpp): -TL1(g)/(h+l2),
    clipped by max_delta_step when set."""
    denom = h + hp.lambda_l2 + extra_l2
    w = jnp.where(denom > 0, -_threshold_l1(g, hp.lambda_l1) / jnp.maximum(denom, 1e-38), 0.0)
    if hp.max_delta_step > 0:
        w = jnp.clip(w, -hp.max_delta_step, hp.max_delta_step)
    return w


def _smoothed(w, cnt, parent_output, hp: SplitHyper):
    """Path smoothing (feature_histogram.hpp USE_SMOOTHING branch):
    w' = w * n/(n+smooth) + parent * smooth/(n+smooth)."""
    if hp.path_smooth <= 0:
        return w
    n = jnp.maximum(cnt, 1.0)
    alpha = n / (n + hp.path_smooth)
    return w * alpha + parent_output * (1.0 - alpha)


def _gain_given_output(g, h, w, hp: SplitHyper, extra_l2: float = 0.0):
    """GetLeafGainGivenOutput: -(2 g w + (h+l2) w^2) - 2 l1 |w| — equals
    TL1(g)^2/(h+l2) at the unconstrained optimum."""
    l2 = hp.lambda_l2 + extra_l2
    return -(2.0 * g * w + (h + l2) * w * w) - 2.0 * hp.lambda_l1 * jnp.abs(w)


def leaf_objective_value(g, h, hp: SplitHyper):
    """Gain of keeping a leaf unsplit (GetLeafGain)."""
    w = calc_leaf_output(g, h, hp)
    return _gain_given_output(g, h, w, hp)


def _split_gain_pair(gl, hl, cl, gr, hr, cr, hp: SplitHyper, *,
                     extra_l2=0.0, parent_output=0.0, lower=None, upper=None,
                     monotone=None, child_bounds=None):
    """Gain of a candidate split + the (possibly constrained) child outputs.

    Broadcasts over any leading shape. Returns (gain, w_left, w_right,
    constraint_ok). ``child_bounds``, when given, carries per-candidate
    (lower_l, upper_l, lower_r, upper_r) arrays (the advanced monotone
    method's per-threshold constraints); it overrides the shared
    [lower, upper] clamp."""
    wl = calc_leaf_output(gl, hl, hp, extra_l2)
    wr = calc_leaf_output(gr, hr, hp, extra_l2)
    wl = _smoothed(wl, cl, parent_output, hp)
    wr = _smoothed(wr, cr, parent_output, hp)
    ok = jnp.ones(jnp.broadcast_shapes(jnp.shape(wl), jnp.shape(wr)), dtype=bool)
    if hp.has_monotone and monotone is not None:
        # basic method (reference: monotone_constraints.hpp:327): child outputs
        # must respect the feature's direction and the leaf's inherited bounds
        viol = ((monotone > 0) & (wl > wr)) | ((monotone < 0) & (wl < wr))
        ok = ok & ~viol
        if child_bounds is not None:
            lo_l, up_l, lo_r, up_r = child_bounds
            wl = jnp.clip(wl, lo_l, up_l)
            wr = jnp.clip(wr, lo_r, up_r)
            # per-child bounds can invert the sibling order after clamping
            # (the shared-clamp path cannot); re-check on clamped outputs
            viol2 = ((monotone > 0) & (wl > wr)) | ((monotone < 0) & (wl < wr))
            ok = ok & ~viol2
        elif lower is not None:
            wl = jnp.clip(wl, lower, upper)
            wr = jnp.clip(wr, lower, upper)
    gain = _gain_given_output(gl, hl, wl, hp, extra_l2) + \
        _gain_given_output(gr, hr, wr, hp, extra_l2)
    return gain, wl, wr, ok


def find_best_split(
    hist: jax.Array,          # (F, B, 3) f32
    parent_sum: jax.Array,    # (3,)
    meta: FeatureMeta,
    feature_mask: jax.Array,  # (F,) bool — col sampling / interaction constraints
    hp: SplitHyper,
    *,
    parent_output: jax.Array = jnp.float32(0.0),
    leaf_lower: jax.Array = jnp.float32(-jnp.inf),
    leaf_upper: jax.Array = jnp.float32(jnp.inf),
    rand_threshold: Optional[jax.Array] = None,  # (F,) extra-trees random bins
    want_feature_gains: bool = False,
    cegb_delta: Optional[jax.Array] = None,      # (F,) CEGB gain penalties
    node_depth: Optional[jax.Array] = None,      # scalar i32 leaf depth
    adv_bounds=None,  # advanced monotone: (lo_l, up_l, lo_r, up_r) (F, B)
    # per-candidate child bounds (reference: monotone_constraints.hpp:856
    # AdvancedLeafConstraints — per-threshold constraints in the scan)
) -> SplitInfo:
    """Best split over all features for one leaf's histogram.

    With ``want_feature_gains`` (static), returns only the per-feature max
    gains (F,) — the voting-parallel learner's local vote input (reference:
    voting_parallel_tree_learner.cpp:322 local top-k votes)."""
    num_feat, num_bin, _ = hist.shape
    b_iota = jnp.arange(num_bin, dtype=jnp.int32)
    bin_valid = b_iota[None, :] < meta.num_bins[:, None]            # (F, B)
    hist = jnp.where(bin_valid[:, :, None], hist, 0.0)
    parent_gain = leaf_objective_value(parent_sum[0], parent_sum[1], hp)

    # ---------- numerical thresholds ----------
    is_missing_bin = meta.movable_missing[:, None] & (b_iota[None, :] == meta.missing_bin[:, None])
    miss = jnp.sum(jnp.where(is_missing_bin[:, :, None], hist, 0.0), axis=1)   # (F, 3)
    hist_nm = jnp.where(is_missing_bin[:, :, None], 0.0, hist)
    cum = jnp.cumsum(hist_nm, axis=1)                                # (F, B, 3)
    total = parent_sum[None, None, :]

    def eval_dir(left):
        right = total - left
        gl, hl, cl = left[..., 0], left[..., 1], left[..., 2]
        gr, hr, cr = right[..., 0], right[..., 1], right[..., 2]
        gain, _, _, ok = _split_gain_pair(
            gl, hl, cl, gr, hr, cr, hp,
            parent_output=parent_output, lower=leaf_lower, upper=leaf_upper,
            monotone=meta.monotone[:, None] if hp.has_monotone else None,
            child_bounds=adv_bounds)
        ok = ok & (cl >= hp.min_data_in_leaf) & (cr >= hp.min_data_in_leaf) \
            & (hl >= hp.min_sum_hessian_in_leaf) & (hr >= hp.min_sum_hessian_in_leaf)
        return jnp.where(ok, gain - parent_gain, NEG_INF)

    # threshold t means bins <= t go left; missing assigned per direction.
    # Both directions ride ONE stacked (2, F, B) eval — _split_gain_pair
    # broadcasts over leading axes, so this halves the per-round op chain
    # the 254-round scan dispatches (split-scan diet).
    t_valid = (b_iota[None, :] < meta.num_bins[:, None] - 1) & ~meta.is_categorical[:, None]
    if rand_threshold is not None:
        # extra-trees: only one random threshold per feature is considered
        # (reference: USE_RAND_SPLIT in FindBestThresholdSequentially)
        t_valid = t_valid & (b_iota[None, :] == rand_threshold[:, None])
    gains2 = eval_dir(jnp.stack([cum, cum + miss[:, None, :]], axis=0))
    # nothing to gain from dl when there is no missing mass; keep dr on ties
    gains2 = jnp.where(
        jnp.stack([t_valid, t_valid & meta.movable_missing[:, None]], axis=0),
        gains2, NEG_INF)
    gain_dr, gain_dl = gains2[0], gains2[1]
    num_gain = jnp.maximum(gain_dr, gain_dl)                 # (F, B)
    num_dl = gain_dl > gain_dr

    # ---------- categorical ----------
    if hp.has_categorical:
        extra_l2 = hp.cat_l2
        # candidate categories exclude the trailing other/missing bin
        cat_bin_ok = meta.is_categorical[:, None] & (b_iota[None, :] < meta.num_bins[:, None] - 1)
        g_b, h_b, c_b = hist[..., 0], hist[..., 1], hist[..., 2]

        # one-vs-rest (reference: one-hot when #cats <= max_cat_to_onehot)
        num_cats = meta.num_bins - 1
        use_onehot = meta.is_categorical & (num_cats <= hp.max_cat_to_onehot)
        left = hist
        right = total - left
        oh_gain, _, _, _ = _split_gain_pair(
            left[..., 0], left[..., 1], left[..., 2],
            right[..., 0], right[..., 1], right[..., 2], hp,
            extra_l2=extra_l2, parent_output=parent_output)
        oh_ok = (left[..., 2] >= hp.min_data_in_leaf) & (right[..., 2] >= hp.min_data_in_leaf) \
            & (left[..., 1] >= hp.min_sum_hessian_in_leaf) \
            & (right[..., 1] >= hp.min_sum_hessian_in_leaf) \
            & cat_bin_ok & use_onehot[:, None] & (c_b > 0)
        oh_gain = jnp.where(oh_ok, oh_gain - parent_gain, NEG_INF)

        # many-vs-many: sort categories by g/(h+cat_smooth), scan prefixes
        # (reference: FindBestThresholdCategoricalInner sorted scan)
        group_ok = cat_bin_ok & (c_b >= hp.min_data_per_group) & ~use_onehot[:, None]
        key = jnp.where(group_ok, g_b / (h_b + hp.cat_smooth), jnp.inf)
        order_asc = jnp.argsort(key, axis=1)
        key_desc = jnp.where(group_ok, g_b / (h_b + hp.cat_smooth), -jnp.inf)
        order_desc = jnp.argsort(-key_desc, axis=1)
        n_groups = jnp.sum(group_ok, axis=1)                         # (F,)

        def mvm_gains(order2):
            # both sort directions in ONE stacked (2, F, B) eval, same
            # collapse as the numerical missing-direction pair above
            h_sorted = jnp.take_along_axis(hist[None], order2[..., None],
                                           axis=2)
            csum = jnp.cumsum(h_sorted, axis=2)                      # prefix of k+1
            k1 = b_iota[None, :] + 1.0                               # prefix size
            left = csum
            right = total - left
            gain, _, _, _ = _split_gain_pair(
                left[..., 0], left[..., 1], left[..., 2],
                right[..., 0], right[..., 1], right[..., 2], hp,
                extra_l2=extra_l2, parent_output=parent_output)
            ok = (k1 <= hp.max_cat_threshold) & (k1 < n_groups[:, None]) \
                & (left[..., 2] >= hp.min_data_in_leaf) & (right[..., 2] >= hp.min_data_in_leaf) \
                & (left[..., 1] >= hp.min_sum_hessian_in_leaf) \
                & (right[..., 1] >= hp.min_sum_hessian_in_leaf)
            return jnp.where(ok, gain - parent_gain, NEG_INF)

        mvm_asc, mvm_desc = mvm_gains(jnp.stack([order_asc, order_desc],
                                                axis=0))
        num_gain = jnp.where(meta.is_categorical[:, None], NEG_INF, num_gain)
    else:
        oh_gain = jnp.full_like(num_gain, NEG_INF)
        mvm_asc = jnp.full_like(num_gain, NEG_INF)
        mvm_desc = jnp.full_like(num_gain, NEG_INF)
        order_asc = order_desc = None
        num_gain = jnp.where(meta.is_categorical[:, None], NEG_INF, num_gain)

    # ---------- combine ----------
    # One live-lane mask and ONE final select instead of a chain of
    # per-adjustment wheres over the full (4, F, B) plane: every adjustment
    # runs unguarded on the adjusted values (keeping the reference op order
    # gain*penalty, *mono_pen, -cegb — bit-identical on live lanes) and
    # dead lanes are forced to -inf once at the end.
    stacked = jnp.stack([num_gain, oh_gain, mvm_asc, mvm_desc], axis=0)  # (4, F, B)
    live = (stacked > NEG_INF) & feature_mask[None, :, None]
    adj = stacked * meta.penalty[None, :, None]
    if hp.has_monotone and hp.monotone_penalty > 0 and node_depth is not None:
        # reference: monotone_constraints.hpp:355 — splits on monotone
        # features at shallow depths are discounted (and forbidden while
        # penalization >= depth + 1)
        p = jnp.float32(hp.monotone_penalty)
        d = node_depth.astype(jnp.float32)
        eps = jnp.float32(K_EPSILON)
        pen = jnp.where(p >= d + 1.0, eps,
                        jnp.where(p <= 1.0, 1.0 - p / (2.0 ** d) + eps,
                                  1.0 - 2.0 ** (p - 1.0 - d) + eps))
        mono_f = meta.monotone != 0
        adj = jnp.where(mono_f[None, :, None], adj * pen, adj)
    if hp.use_cegb and cegb_delta is not None:
        adj = adj - cegb_delta[None, :, None]
    stacked = jnp.where(live, adj, NEG_INF)
    if want_feature_gains:
        return jnp.max(stacked, axis=(0, 2))                 # (F,)
    flat = stacked.reshape(-1)
    best_idx = jnp.argmax(flat)
    best_gain = flat[best_idx]
    kind = (best_idx // (num_feat * num_bin)).astype(jnp.int32)
    rem = best_idx % (num_feat * num_bin)
    feat = (rem // num_bin).astype(jnp.int32)
    tbin = (rem % num_bin).astype(jnp.int32)

    # ---------- routing table for the winner ----------
    def tbl_numerical():
        base = b_iota <= tbin
        dl = num_dl[feat, tbin]
        base = jnp.where(meta.movable_missing[feat] & (b_iota == meta.missing_bin[feat]),
                         dl, base)
        return base, dl

    def tbl_onehot():
        return b_iota == tbin, jnp.bool_(False)

    def tbl_mvm(order):
        row = order[feat]
        prefix = b_iota <= tbin                      # first (tbin+1) sorted bins
        tbl = jnp.zeros((num_bin,), bool).at[row].set(prefix)
        return tbl, jnp.bool_(False)

    if hp.has_categorical:
        go_left, default_left = jax.lax.switch(
            kind,
            [lambda: tbl_numerical(), lambda: tbl_onehot(),
             lambda: tbl_mvm(order_asc), lambda: tbl_mvm(order_desc)],
        )
    else:
        go_left, default_left = tbl_numerical()

    left_sum = jnp.sum(jnp.where(go_left[None, :, None], hist[feat][None], 0.0), axis=(0, 1))
    right_sum = parent_sum - left_sum
    is_cat_win = kind > 0
    extra = jnp.where(is_cat_win, hp.cat_l2, 0.0)
    wl = _smoothed(calc_leaf_output(left_sum[0], left_sum[1], hp, extra),
                   left_sum[2], parent_output, hp)
    wr = _smoothed(calc_leaf_output(right_sum[0], right_sum[1], hp, extra),
                   right_sum[2], parent_output, hp)
    if hp.has_monotone:
        if adv_bounds is not None:
            lo_l, up_l, lo_r, up_r = adv_bounds
            wl = jnp.clip(wl, lo_l[feat, tbin], up_l[feat, tbin])
            wr = jnp.clip(wr, lo_r[feat, tbin], up_r[feat, tbin])
        else:
            wl = jnp.clip(wl, leaf_lower, leaf_upper)
            wr = jnp.clip(wr, leaf_lower, leaf_upper)

    valid = best_gain > jnp.float32(hp.min_gain_to_split)
    best_gain = jnp.where(valid, best_gain, NEG_INF)
    return SplitInfo(
        gain=best_gain.astype(jnp.float32),
        feature=feat,
        bin=tbin,
        kind=kind,
        default_left=default_left,
        go_left=go_left,
        left_sum=left_sum,
        right_sum=right_sum,
        left_output=wl.astype(jnp.float32),
        right_output=wr.astype(jnp.float32),
    )
