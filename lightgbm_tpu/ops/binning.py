"""Feature discretization: value -> bin mapping.

TPU-native equivalent of the reference's ``BinMapper``
(reference: include/LightGBM/bin.h:61, src/io/bin.cpp:325 FindBin):
equal-density numerical bins found from sampled values, a dedicated zero bin,
categorical bin dictionaries sorted by frequency, missing-value handling
(None/Zero/NaN, reference bin.h:26), per-feature max_bin override, and
trivial-feature detection.

Host-side (numpy): binning runs once at Dataset construction; the result is a
uint8/uint16 (rows, features) matrix that lives in device HBM.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

# guards the lazy sorted-category views: serving threads bin rows for
# the forest path (session._bin_rows) concurrently with main-thread
# predicts on the same mappers
_SORT_LOCK = threading.Lock()

# Values with |x| <= kZeroThreshold fall into the zero bin
# (reference: include/LightGBM/bin.h:33 kZeroThreshold = 1e-35).
K_ZERO_THRESHOLD = 1e-35

# missing handling modes (reference: include/LightGBM/bin.h:26 MissingType)
MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1


@dataclass
class BinMapper:
    """Per-feature value->bin discretizer."""

    num_bins: int = 1
    bin_type: int = BIN_NUMERICAL
    missing_type: int = MISSING_NONE
    is_trivial: bool = True
    # numerical: bin k covers (upper_bounds[k-1], upper_bounds[k]]
    upper_bounds: np.ndarray = field(default_factory=lambda: np.array([np.inf]))
    # categorical: bin index -> category value (sorted by descending frequency)
    categories: np.ndarray = field(default_factory=lambda: np.array([], dtype=np.int64))
    default_bin: int = 0       # bin of the value 0.0 (reference bin.h:138 GetDefaultBin)
    most_freq_bin: int = 0     # bin with the most sampled data (reference bin.h:144)
    missing_bin: int = 0       # bin holding missing values (NaN bin or zero bin)
    sparse_rate: float = 0.0   # fraction of zeros in the sample (drives EFB)
    min_value: float = 0.0
    max_value: float = 0.0

    # lazy sorted views for vectorized categorical lookup
    _sorted_cats: Optional[np.ndarray] = None
    _sorted_order: Optional[np.ndarray] = None

    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin (reference: bin.h:464 ValueToBin binary search)."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_CATEGORICAL:
            if len(self.categories) == 0:
                return np.full(values.shape, self.missing_bin, dtype=np.int64)
            with _SORT_LOCK:
                if self._sorted_cats is None:
                    self._sorted_order = np.argsort(self.categories,
                                                    kind="stable")
                    self._sorted_cats = self.categories[self._sorted_order]
                scats, sorder = self._sorted_cats, self._sorted_order
            ivals = np.where(np.isfinite(values), values, -1).astype(np.int64)
            pos = np.searchsorted(scats, ivals)
            pos = np.clip(pos, 0, len(self.categories) - 1)
            hit = scats[pos] == ivals
            out = np.where(hit, sorder[pos], self.missing_bin)
            return out.astype(np.int64)
        # numerical
        nan_mask = np.isnan(values)
        if self.missing_type != MISSING_NAN:
            # Zero/None: NaN is treated as zero (reference bin.h ValueToBin)
            values = np.where(nan_mask, 0.0, values)
        bins = np.searchsorted(self.upper_bounds, values, side="left")
        bins = np.minimum(bins, self.num_bins - 1)
        if self.missing_type == MISSING_NAN:
            bins = np.where(nan_mask, self.missing_bin, bins)
        return bins.astype(np.int64)

    def bin_to_value(self, b: int) -> float:
        """Representative threshold value for a bin upper bound (used for
        model serialization; reference stores real-valued thresholds in trees)."""
        if self.bin_type == BIN_CATEGORICAL:
            if 0 <= b < len(self.categories):
                return float(self.categories[b])
            return -1.0
        return float(self.upper_bounds[min(b, self.num_bins - 1)])


def _greedy_find_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    total_cnt: int,
    max_bin: int,
    min_data_in_bin: int,
) -> List[float]:
    """Equal-density bin upper bounds over distinct sampled values.

    Re-derivation of the reference's GreedyFindBin (src/io/bin.cpp:87):
    if few distinct values each gets its own bin; otherwise target
    mean_bin_size = cnt/max_bin with min_data_in_bin enforced, and any
    distinct value whose count exceeds mean_bin_size is forced into its own
    bin ("big" values), re-computing the mean over the rest.
    """
    n = len(distinct_values)
    bounds: List[float] = []
    if n == 0:
        return [np.inf]
    if n <= max_bin:
        cur = 0
        for i in range(n - 1):
            cur += counts[i]
            if cur >= min_data_in_bin or min_data_in_bin <= 1:
                bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                cur = 0
        bounds.append(np.inf)
        return bounds
    # More distinct values than bins: equal-density with "big value"
    # carve-out. Iterates per BIN (<= max_bin steps of searchsorted over the
    # cumulative counts) instead of per distinct value — the per-value loop
    # cost ~50 ms/feature at a 200k sample, dominating Dataset construction.
    # Greedy close rule per value index i (reference GreedyFindBin order):
    #   a) counts[i] is "big"  b) bin count >= mean and >= min_data_in_bin
    #   c) counts[i+1] is big and bin count >= max(1, min_data_in_bin)
    max_bin = max(1, max_bin)
    mean_size = total_cnt / max_bin
    is_big = counts > mean_size
    rest_cnt = total_cnt - counts[is_big].sum()
    rest_bins = max_bin - int(is_big.sum())
    if rest_bins > 0:
        mean_size = rest_cnt / rest_bins
    else:
        mean_size = np.inf
    csum = np.cumsum(counts, dtype=np.float64)
    big_pos = np.flatnonzero(is_big)                  # ascending value indexes
    pre_big = big_pos[big_pos > 0] - 1                # i with is_big[i+1]
    min_d = float(min_data_in_bin)
    need_b_extra = max(mean_size, min_d)
    need_c_extra = max(1.0, min_d)
    start = 0
    base = 0.0                                        # csum before `start`
    while start < n and len(bounds) < max_bin - 1:
        # first i >= start satisfying each close rule
        k = np.searchsorted(big_pos, start)
        i_a = int(big_pos[k]) if k < len(big_pos) else n
        i_b = int(np.searchsorted(csum, base + need_b_extra, side="left")) \
            if np.isfinite(need_b_extra) else n
        # rule c needs BOTH is_big[i+1] and the count condition at the same
        # i; pre-big positions are sorted and the count condition is
        # i >= first index reaching base + need_c
        i_c_cnt = int(np.searchsorted(csum, base + need_c_extra, side="left"))
        kc = np.searchsorted(pre_big, max(start, i_c_cnt))
        i_c = int(pre_big[kc]) if kc < len(pre_big) else n
        close = min(i_a, i_b, i_c)
        if close >= n - 1:
            break
        bounds.append((distinct_values[close] + distinct_values[close + 1]) / 2.0)
        start = close + 1
        base = float(csum[close])
    bounds.append(np.inf)
    return bounds


def find_bin(
    sample_values: np.ndarray,
    total_sample_cnt: int,
    max_bin: int,
    min_data_in_bin: int = 3,
    *,
    bin_type: int = BIN_NUMERICAL,
    use_missing: bool = True,
    zero_as_missing: bool = False,
    forced_bounds: Optional[Sequence[float]] = None,
    min_split_data: int = 0,
) -> BinMapper:
    """Find the bin mapping for one feature from sampled values.

    Mirrors reference BinMapper::FindBin (src/io/bin.cpp:325). ``sample_values``
    are the sampled raw values INCLUDING zeros and NaNs; ``total_sample_cnt``
    is the number of sampled rows (zeros may be implicit in sparse input — the
    difference ``total_sample_cnt - len(sample_values)`` counts as zeros).
    """
    m = BinMapper()
    m.bin_type = bin_type
    vals = np.asarray(sample_values, dtype=np.float64).ravel()
    na_cnt = int(np.isnan(vals).sum())
    vals = vals[~np.isnan(vals)]
    implicit_zero = max(0, total_sample_cnt - len(vals) - na_cnt)
    zero_cnt = int((np.abs(vals) <= K_ZERO_THRESHOLD).sum()) + implicit_zero

    if bin_type == BIN_CATEGORICAL:
        return _find_bin_categorical(m, vals, na_cnt, zero_cnt, max_bin, min_data_in_bin,
                                     total_sample_cnt)

    # ---- numerical ----
    if zero_as_missing:
        # zeros are missing: they join NaN in the zero bin (reference FindBin
        # with zero_as_missing: missing_type = Zero). The zero bin must still
        # be reserved — zero_cnt keeps counting so the bin layout below
        # allocates it and sparse_rate/EFB stay correct.
        na_cnt += zero_cnt
        m.missing_type = MISSING_ZERO
    elif not use_missing:
        m.missing_type = MISSING_NONE
        # NaNs treated as zeros
        zero_cnt += na_cnt
        na_cnt = 0
    elif na_cnt > 0:
        m.missing_type = MISSING_NAN
    else:
        m.missing_type = MISSING_NONE

    nonzero = vals[np.abs(vals) > K_ZERO_THRESHOLD]
    m.min_value = float(nonzero.min()) if len(nonzero) else 0.0
    m.max_value = float(nonzero.max()) if len(nonzero) else 0.0

    n_avail = max_bin - (1 if m.missing_type == MISSING_NAN else 0)
    forced_inner: List[float] = []
    if forced_bounds is not None and len(forced_bounds) > 0:
        # forced bounds are INSERTED; the remaining budget still fills with
        # density bins (reference: DatasetLoader::GetForcedBins + FindBin
        # with forced_upper_bounds, bin.cpp:325)
        forced_inner = sorted(float(b) for b in forced_bounds
                              if np.isfinite(b))
        n_avail = max(n_avail - len(forced_inner), 2)
    if True:
        neg = nonzero[nonzero < -K_ZERO_THRESHOLD]
        pos = nonzero[nonzero > K_ZERO_THRESHOLD]
        # split bin budget between negative / zero / positive regions by density
        # then merge (reference FindBinWithZeroAsOneBin: zero always gets one bin)
        total_for_density = len(neg) + len(pos) + (zero_cnt if zero_cnt > 0 else 0)
        if total_for_density == 0:
            total_for_density = 1
        bounds_list: List[float] = []
        n_zero_bin = 1 if zero_cnt > 0 or (len(neg) and len(pos)) else 0
        budget = max(1, n_avail - n_zero_bin)
        n_neg_bins = int(round(budget * (len(neg) / total_for_density))) if len(neg) else 0
        n_pos_bins = budget - n_neg_bins
        if len(neg):
            dv, cnts = np.unique(neg, return_counts=True)
            b = _greedy_find_bin(dv, cnts, len(neg), max(1, n_neg_bins), min_data_in_bin)
            bounds_list.extend(x for x in b if x < np.inf)
            bounds_list.append(-K_ZERO_THRESHOLD)  # close the negative region
        if n_zero_bin and len(pos):
            bounds_list.append(K_ZERO_THRESHOLD)   # zero bin (−kzt, +kzt]
        if len(pos):
            dv, cnts = np.unique(pos, return_counts=True)
            b = _greedy_find_bin(dv, cnts, len(pos), max(1, n_pos_bins), min_data_in_bin)
            bounds_list.extend(x for x in b if x < np.inf)
        bounds = sorted(set(bounds_list))
        bounds.append(np.inf)

    if forced_inner:
        bounds = sorted(set(list(bounds) + forced_inner))
    m.upper_bounds = np.asarray(bounds, dtype=np.float64)
    num_value_bins = len(bounds)
    if m.missing_type == MISSING_NAN:
        m.num_bins = num_value_bins + 1
        m.missing_bin = num_value_bins  # last bin holds NaN
    else:
        m.num_bins = num_value_bins
    # zero/default bin (reference bin.h:138 GetDefaultBin)
    m.default_bin = int(np.searchsorted(m.upper_bounds, 0.0, side="left"))
    m.default_bin = min(m.default_bin, num_value_bins - 1)
    if m.missing_type == MISSING_ZERO:
        m.missing_bin = m.default_bin

    # trivial feature: a single effective bin -> no split possible
    m.is_trivial = m.num_bins <= 1 or (num_value_bins <= 1 and na_cnt == 0)
    if min_split_data > 0 and not m.is_trivial:
        # prune features that cannot satisfy min_data_in_leaf on any side
        # (reference: feature_pre_filter via FindBin min_split_data arg)
        counts = np.bincount(
            np.clip(np.searchsorted(m.upper_bounds, vals, side="left"), 0, num_value_bins - 1),
            minlength=num_value_bins,
        )
        counts[m.default_bin] += implicit_zero
        csum = np.cumsum(counts)
        ok = np.any((csum[:-1] >= min_split_data) & (csum[-1] - csum[:-1] >= min_split_data))
        m.is_trivial = not bool(ok)

    # most frequent bin on the sample
    bins_sample = m.value_to_bin(np.concatenate([vals, np.full(implicit_zero, 0.0)]))
    if len(bins_sample):
        m.most_freq_bin = int(np.bincount(bins_sample, minlength=m.num_bins).argmax())
    m.sparse_rate = zero_cnt / max(1, total_sample_cnt)
    return m


def _find_bin_categorical(
    m: BinMapper,
    vals: np.ndarray,
    na_cnt: int,
    zero_cnt: int,
    max_bin: int,
    min_data_in_bin: int,
    total_sample_cnt: int,
) -> BinMapper:
    """Categorical dictionary: categories sorted by descending frequency, rare
    categories cut (reference src/io/bin.cpp categorical branch: cut categories
    after max_bin and warn on high cardinality; unseen/rare -> treated as the
    'other' NaN bin)."""
    ivals = vals.astype(np.int64)
    if len(ivals) and ivals.min() < 0:
        ivals = ivals[ivals >= 0]  # negative categories treated as missing
        na_cnt += len(vals) - len(ivals)
    cats, counts = (np.unique(ivals, return_counts=True) if len(ivals)
                    else (np.array([], dtype=np.int64), np.array([], dtype=np.int64)))
    order = np.argsort(-counts, kind="stable")
    cats, counts = cats[order], counts[order]
    # cut: keep top max_bin-1 (reserve one bin for other/missing)
    keep = min(len(cats), max_bin - 1)
    # also drop categories with very low count (reference keeps 99% mass)
    if keep < len(cats):
        cats, counts = cats[:keep], counts[:keep]
    m.categories = cats
    m.num_bins = len(cats) + 1  # +1 other/missing bin (last)
    m.missing_bin = len(cats)
    m.missing_type = MISSING_NAN
    m.default_bin = 0
    m.most_freq_bin = 0 if len(cats) else m.missing_bin
    m.is_trivial = len(cats) <= 1
    m.sparse_rate = zero_cnt / max(1, total_sample_cnt)
    return m
