"""Gradient/hessian histogram construction on the MXU.

TPU-native replacement for the reference's histogram kernels — the CPU
col-wise/row-wise paths (reference: src/io/dense_bin.hpp:98
ConstructHistogramInner, src/io/train_share_states.h:46) and the OpenCL/CUDA
kernels (src/treelearner/ocl/histogram256.cl,
src/treelearner/kernels/histogram_16_64_256.cu). Design:

- The binned matrix is dense ``(rows, features)`` int8/int16 in HBM. A
  histogram is ``(features, max_bins, 3)`` float32 of (sum_grad, sum_hess,
  count). The count channel replaces the reference's hessian-derived
  ``cnt_factor`` trick (feature_histogram.hpp:316) exactly.
- Accumulation is a one-hot × (g,h,cnt) matmul: bins one-hot encodes to
  ``(chunk, F*B)`` and a single ``(F*B, chunk) @ (chunk, 3)`` contraction
  rides the MXU. TPUs have no fast scatter-add; this keeps the hot op a
  matmul (SURVEY.md §7 "Scatter-add histogram throughput").
- Rows are processed in chunks under ``lax.scan`` so the transient one-hot
  stays small; masking (leaf membership, bagging) is pre-multiplied into the
  (g,h,cnt) channels so the same kernel serves root and per-leaf histograms.
- float32 accumulation follows the reference GPU precedent
  (config.h gpu_use_dp=false default; docs/GPU-Performance.rst accuracy
  tables) rather than the CPU's double hist_t.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_CHUNK = 4096


def _hist_chunk(bins_c: jax.Array, ghc_c: jax.Array, num_bins: int,
                mxu_bf16: bool = False) -> jax.Array:
    """(chunk, F) int bins + (chunk, C) channels -> (F*B, C) partial histogram.

    Contraction order is (C, chunk) @ (chunk, F*B): the wide F*B axis sits on
    the MXU's 128-lane output dimension; the tiny channel axis pads only the
    sublane side. On TPU (``mxu_bf16``) the one-hot materializes in bfloat16
    (exact 0/1, half the HBM traffic — this pass is bandwidth-bound) and the
    f32 channels split hi+lo so two bf16 MXU passes keep f32 accuracy; on CPU
    everything stays exact f32 for the test reference.
    """
    chunk, num_feat = bins_c.shape
    iota = jnp.arange(num_bins, dtype=bins_c.dtype)
    onehot = (bins_c[:, :, None] == iota).reshape(chunk, num_feat * num_bins)
    if mxu_bf16:
        oh = onehot.astype(jnp.bfloat16)
        hi = ghc_c.astype(jnp.bfloat16)
        lo = (ghc_c - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        out = jax.lax.dot(hi.T, oh, preferred_element_type=jnp.float32)
        out = out + jax.lax.dot(lo.T, oh, preferred_element_type=jnp.float32)
        return out.T
    out = jax.lax.dot(ghc_c.astype(jnp.float32).T, onehot.astype(jnp.float32),
                      precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)
    return out.T


def build_histogram(
    bins: jax.Array,
    ghc: jax.Array,
    num_bins: int,
    chunk: int = DEFAULT_CHUNK,
    mxu_bf16: bool = False,
) -> jax.Array:
    """Accumulate ``(F, num_bins, C)`` histogram of channel sums per bin.

    bins: (N, F) integer bin codes; ghc: (N, C) float32 channels, already
    masked/weighted (out-of-leaf and out-of-bag rows carry zeros).
    """
    n, num_feat = bins.shape
    c = ghc.shape[1]
    chunk = min(chunk, max(1, n))
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, pad), (0, 0)))
    nchunks = (n + pad) // chunk
    if nchunks == 1:
        flat = _hist_chunk(bins, ghc, num_bins, mxu_bf16)
        return flat.reshape(num_feat, num_bins, c)

    bins_r = bins.reshape(nchunks, chunk, num_feat)
    ghc_r = ghc.reshape(nchunks, chunk, c)

    def body(acc, xs):
        b, g = xs
        return acc + _hist_chunk(b, g, num_bins, mxu_bf16), None

    acc0 = jnp.zeros((num_feat * num_bins, c), dtype=jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (bins_r, ghc_r))
    return acc.reshape(num_feat, num_bins, c)


def build_histogram_np(bins: np.ndarray, ghc: np.ndarray, num_bins: int) -> np.ndarray:
    """Reference host implementation (used by tests to validate the MXU path)."""
    n, num_feat = bins.shape
    c = ghc.shape[1]
    out = np.zeros((num_feat, num_bins, c), dtype=np.float64)
    for f in range(num_feat):
        for ch in range(c):
            out[f, :, ch] = np.bincount(bins[:, f], weights=ghc[:, ch], minlength=num_bins)
    return out.astype(np.float32)


@partial(jax.jit, static_argnames=("num_bins", "chunk", "mxu_bf16"))
def build_histogram_jit(bins, ghc, num_bins: int, chunk: int = DEFAULT_CHUNK,
                        mxu_bf16: bool = False):
    return build_histogram(bins, ghc, num_bins, chunk, mxu_bf16)
