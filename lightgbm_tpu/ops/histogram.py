"""Gradient/hessian histogram construction on the MXU.

TPU-native replacement for the reference's histogram kernels — the CPU
col-wise/row-wise paths (reference: src/io/dense_bin.hpp:98
ConstructHistogramInner, src/io/train_share_states.h:46) and the OpenCL/CUDA
kernels (src/treelearner/ocl/histogram256.cl,
src/treelearner/kernels/histogram_16_64_256.cu). Design:

- The binned matrix is dense ``(rows, features)`` int8/int16 in HBM. A
  histogram is ``(features, max_bins, 3)`` float32 of (sum_grad, sum_hess,
  count). The count channel replaces the reference's hessian-derived
  ``cnt_factor`` trick (feature_histogram.hpp:316) exactly.
- Accumulation is a one-hot × (g,h,cnt) matmul: bins one-hot encodes to
  ``(chunk, F*B)`` and a single ``(F*B, chunk) @ (chunk, 3)`` contraction
  rides the MXU. TPUs have no fast scatter-add; this keeps the hot op a
  matmul (SURVEY.md §7 "Scatter-add histogram throughput").
- Rows are processed in chunks under ``lax.scan`` so the transient one-hot
  stays small; masking (leaf membership, bagging) is pre-multiplied into the
  (g,h,cnt) channels so the same kernel serves root and per-leaf histograms.
- float32 accumulation follows the reference GPU precedent
  (config.h gpu_use_dp=false default; docs/GPU-Performance.rst accuracy
  tables) rather than the CPU's double hist_t.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import trace_phase, track_jit

try:  # pallas is optional at import time (CPU test meshes use XLA paths)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    if not hasattr(pltpu, "HBM"):  # older jax spells these differently
        pltpu.HBM = pltpu.ANY
        pltpu.CompilerParams = pltpu.TPUCompilerParams
except Exception:  # pragma: no cover
    pl = pltpu = None

DEFAULT_CHUNK = 4096


def _hist_chunk(bins_c: jax.Array, ghc_c: jax.Array, num_bins: int,
                mxu_bf16: bool = False) -> jax.Array:
    """(chunk, F) int bins + (chunk, C) channels -> (F*B, C) partial histogram.

    Contraction order is (C, chunk) @ (chunk, F*B): the wide F*B axis sits on
    the MXU's 128-lane output dimension; the tiny channel axis pads only the
    sublane side. On TPU (``mxu_bf16``) the one-hot materializes in bfloat16
    (exact 0/1, half the HBM traffic — this pass is bandwidth-bound) and the
    f32 channels split hi+lo so two bf16 MXU passes keep f32 accuracy; on CPU
    everything stays exact f32 for the test reference.
    """
    chunk, num_feat = bins_c.shape
    iota = jnp.arange(num_bins, dtype=bins_c.dtype)
    onehot = (bins_c[:, :, None] == iota).reshape(chunk, num_feat * num_bins)
    if mxu_bf16:
        oh = onehot.astype(jnp.bfloat16)
        hi = jax.lax.optimization_barrier(ghc_c.astype(jnp.bfloat16))
        lo = (ghc_c - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        out = jax.lax.dot(hi.T, oh, preferred_element_type=jnp.float32)
        out = out + jax.lax.dot(lo.T, oh, preferred_element_type=jnp.float32)
        return out.T
    out = jax.lax.dot(ghc_c.astype(jnp.float32).T, onehot.astype(jnp.float32),
                      precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)
    return out.T


def build_histogram(
    bins: jax.Array,
    ghc: jax.Array,
    num_bins: int,
    chunk: int = DEFAULT_CHUNK,
    mxu_bf16: bool = False,
) -> jax.Array:
    """Accumulate ``(F, num_bins, C)`` histogram of channel sums per bin.

    bins: (N, F) integer bin codes; ghc: (N, C) float32 channels, already
    masked/weighted (out-of-leaf and out-of-bag rows carry zeros).
    """
    n, num_feat = bins.shape
    c = ghc.shape[1]
    chunk = min(chunk, max(1, n))
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, pad), (0, 0)))
    nchunks = (n + pad) // chunk
    if nchunks == 1:
        flat = _hist_chunk(bins, ghc, num_bins, mxu_bf16)
        return flat.reshape(num_feat, num_bins, c)

    bins_r = bins.reshape(nchunks, chunk, num_feat)
    ghc_r = ghc.reshape(nchunks, chunk, c)

    def body(acc, xs):
        b, g = xs
        return acc + _hist_chunk(b, g, num_bins, mxu_bf16), None

    acc0 = jnp.zeros((num_feat * num_bins, c), dtype=jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (bins_r, ghc_r))
    return acc.reshape(num_feat, num_bins, c)


def build_histogram_np(bins: np.ndarray, ghc: np.ndarray, num_bins: int) -> np.ndarray:
    """Reference host implementation (used by tests to validate the MXU path)."""
    n, num_feat = bins.shape
    c = ghc.shape[1]
    out = np.zeros((num_feat, num_bins, c), dtype=np.float64)
    for f in range(num_feat):
        for ch in range(c):
            out[f, :, ch] = np.bincount(bins[:, f], weights=ghc[:, ch], minlength=num_bins)
    return out.astype(np.float32)


@partial(jax.jit, static_argnames=("num_bins", "chunk", "mxu_bf16"))
def build_histogram_jit(bins, ghc, num_bins: int, chunk: int = DEFAULT_CHUNK,
                        mxu_bf16: bool = False):
    return build_histogram(bins, ghc, num_bins, chunk, mxu_bf16)


build_histogram_jit = track_jit("ops/build_histogram", build_histogram_jit)


# ---------------------------------------------------------------------------
# Segment histogram (partitioned learner path)
# ---------------------------------------------------------------------------
#
# With rows kept leaf-contiguous (ops/partition.py), a leaf histogram reads
# exactly the child's segment — the reference's O(rows_in_leaf) contract
# (dense_bin.hpp:98). The direct one-hot matmul wastes the MXU (3-wide
# output) and materializes (rows, F*B) one-hots; instead the bin id is
# decomposed b = lo_w*hi + lo and the histogram factorizes as
#   H[f,hi,lo,c] = sum_n HiOH[n,f,hi] * (LoOH[n,f,lo] * ch[n,c])
# — a feature-batched einsum whose operands are (rows, F, B/lo_w) and
# (rows, F, lo_w*NCH): far less materialization than the direct form.
# The split width trades the two operands against each other AND shapes
# the per-feature matmul (M = B/lo_w — larger M tiles the MXU better):
# measured on v5e at B=256, full-N segments: lo_w=16 -> 8 -> 4 runs
# 22.2 / 14.1 / 10.3 ms at (2M, F=28) and 29.3 / 11.4 / 12.5 ms at
# (500K, F=137); lo_w=2 collapses (~105 ms). Auto choice: 4 for F <= 64,
# 8 above. Exactness: bf16 (hi, lo) channel splits make every product
# exactly representable; the MXU accumulates f32 — the reference's GPU
# f32-histogram precedent (docs/GPU-Performance.rst); all widths are
# bit-identical.

LO_W = 16  # legacy default for callers that don't pick per-shape


def auto_lo_w(num_feat: int) -> int:
    return 4 if num_feat <= 64 else 8


def _split_bf16(x):
    # the barrier keeps XLA from folding the round-trip under
    # --xla_allow_excess_precision (which would simplify lo to zero)
    hi = jax.lax.optimization_barrier(x.astype(jnp.bfloat16))
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _mxu_dtype():
    """bf16 operands on TPU (MXU accumulates f32 — verified pair-exact);
    f32 elsewhere (XLA CPU accumulates bf16 dots in bf16, which would lose
    the pair correction)."""
    return jnp.bfloat16 if jax.default_backend() in ("tpu", "axon") \
        else jnp.float32


_LO_SHIFT = {2: 1, 4: 2, 8: 3, 16: 4}


def _hist16_chunk(cb, cgm, num_bins: int, exact: bool, lo_w: int = LO_W):
    """(C, F) u8 + (C, 3) f32 masked channels -> (F, SH, lo_w*NCH) f32."""
    dt = _mxu_dtype()
    sh = (num_bins + lo_w - 1) // lo_w
    hi = (cb >> _LO_SHIFT[lo_w]).astype(jnp.uint8)
    lo = (cb & (lo_w - 1)).astype(jnp.uint8)
    hi_oh = (hi[:, :, None] == jnp.arange(sh, dtype=jnp.uint8)) \
        .astype(dt)                                          # (C, F, SH)
    lo_oh = (lo[:, :, None] == jnp.arange(lo_w, dtype=jnp.uint8))
    if exact:
        g_hi, g_lo = _split_bf16(cgm[:, 0])
        h_hi, h_lo = _split_bf16(cgm[:, 1])
        ch = jnp.stack([g_hi, g_lo, h_hi, h_lo,
                        cgm[:, 2].astype(jnp.bfloat16)], axis=1)  # (C, 5)
    else:
        ch = cgm.astype(jnp.bfloat16)                        # (C, 3)
    nch = ch.shape[1]
    c, f = cb.shape
    log_ = (lo_oh[:, :, :, None].astype(dt)
            * ch[:, None, None, :].astype(dt)).reshape(c, f, lo_w * nch)
    return jnp.einsum("cfh,cfx->fhx", hi_oh, log_,
                      preferred_element_type=jnp.float32)


def _hist16_combine(acc, num_bins: int, exact: bool, lo_w: int = LO_W):
    f, sh, _ = acc.shape
    nch = 5 if exact else 3
    h = acc.reshape(f, sh, lo_w, nch).reshape(f, sh * lo_w, nch)[:, :num_bins]
    if exact:
        return jnp.stack([h[..., 0] + h[..., 1],
                          h[..., 2] + h[..., 3], h[..., 4]], axis=-1)
    return h


def _hist16_chunk_int8(cb, gq, hq, cnt, valid, num_bins: int,
                       lo_w: int = LO_W):
    """int8 quantized chunk: one-hot x int8 dots accumulate in int32 on the
    MXU at 2x bf16 peak with ~2.5x less operand materialization."""
    sh = (num_bins + lo_w - 1) // lo_w
    hi = (cb >> _LO_SHIFT[lo_w]).astype(jnp.uint8)
    lo = (cb & (lo_w - 1)).astype(jnp.uint8)
    hi_oh = (hi[:, :, None] == jnp.arange(sh, dtype=jnp.uint8)) \
        .astype(jnp.int8)                                    # (C, F, SH)
    lo_oh = (lo[:, :, None] == jnp.arange(lo_w, dtype=jnp.uint8))
    v = valid.astype(jnp.int8)
    ch = jnp.stack([gq.astype(jnp.int8) * v, hq.astype(jnp.int8) * v,
                    cnt.astype(jnp.int8) * v], axis=1)       # (C, 3)
    c, f = cb.shape
    log_ = (lo_oh[:, :, :, None].astype(jnp.int8)
            * ch[:, None, None, :]).reshape(c, f, lo_w * 3)
    return jnp.einsum("cfh,cfx->fhx", hi_oh, log_,
                      preferred_element_type=jnp.int32)


def hist16_segment_q(work: jax.Array, plane, start, cnt, gscale, hscale, *,
                     num_bins: int, num_feat: int,
                     chunk: int = 2048, lo_w: int = 0) -> jax.Array:
    """int8-quantized segment histogram -> dequantized (F, num_bins, 3) f32.

    work rows are (F + 3) u8: bins then int8 g, int8 h, u8 cnt
    (ops/partition.py pack_rows_quantized). int32 accumulation bounds rows
    at ~16M per leaf (127 * N < 2^31).
    """
    from .partition import unpack_ghq

    f = num_feat
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nchunks = (cnt + chunk - 1) // chunk
    width = work.shape[2]

    def body(i, acc):
        off = start + i * chunk
        cw = jax.lax.dynamic_slice(work, (plane, off, 0),
                                   (1, chunk, width))[0]
        cb = cw[:, :f]
        gq, hq, cq = unpack_ghq(cw, f)
        rows_left = cnt - i * chunk
        valid = jnp.arange(chunk, dtype=jnp.int32) < rows_left
        return acc + _hist16_chunk_int8(cb, gq, hq, cq, valid, num_bins, lo_w)

    acc = jax.lax.fori_loop(
        0, nchunks, body,
        jnp.zeros((f, sh, lo_w * 3), jnp.int32))
    h = acc.reshape(f, sh, lo_w, 3).reshape(f, sh * lo_w, 3)[:, :num_bins]
    scale = jnp.stack([1.0 / gscale, 1.0 / hscale,
                       jnp.float32(1.0)])
    return h.astype(jnp.float32) * scale[None, None, :]


# ---------------------------------------------------------------------------
# In-VMEM Pallas segment histogram
# ---------------------------------------------------------------------------
#
# The XLA einsum path below is compute-near-optimal per chunk (the one-hot
# builds are VPU-bound), but each dynamic-trip loop iteration drags ~7.7 us
# of parasitic fusions (operand copies, valid-mask broadcasts, accumulator
# shuffling — profiled: copy.216 / broadcast.2689 / broadcast_multiply /
# dynamic-slice fusions) plus XLA while-loop overhead. This kernel runs the
# SAME hi/lo factorization with the chunk loop, channel splits and (F, SH,
# lo_w*5) accumulator all resident in VMEM: HBM traffic is one streamed
# read of the segment, and per-chunk overhead is one double-buffered DMA.
# Accumulation order matches the einsum path chunk-for-chunk (bit-identical
# at the same chunk size). Reference analog: the OpenCL histogram kernels'
# local-memory accumulators (src/treelearner/ocl/histogram256.cl:600).
#
# Mosaic notes: u8 lane tiles force W % 128 == 0 (the partitioned work
# buffer guarantees it); f32 words re-assemble from their 4 bytes with
# MULTIPLIES (vector << by >= 16 miscompiles on this toolchain — measured);
# one dot per feature (SH, C) x (C, lo_w*5) — pair-batching features into
# M=128 doubles the MACs for the cross blocks and wins nothing.


def _hist_pallas_kernel(sref, work_in, work_ref, acc_ref, cin, acc_s, sem,
                        *, ch, width, num_feat, sh, lo_w, nch):
    # work_ref is never written: it exists so the buffer ALIASES through
    # this call. Without it, XLA materializes a defensive copy of the whole
    # work buffer before every histogram (the partition kernel donates the
    # same buffer in the same loop body) — measured +100 ms/iter at 2M rows.
    # acc accumulates in SCRATCH and DMAs to the HBM output at the end: an
    # ungridded VMEM-spec output (like a VMEM-spec input) drops the call
    # onto a ~0.45 ms/call slow dispatch path.
    f32 = jnp.float32
    i32 = jnp.int32
    plane = sref[0]
    start = sref[1]
    cnt = sref[2]
    F = num_feat

    astart = (start // 32) * 32
    head = start - astart
    tot = head + cnt
    nchunks = jnp.maximum((tot + ch - 1) // ch, 1)

    acc_s[...] = jnp.zeros((F * sh, lo_w * nch), f32)

    def start_in(i, slot):
        # the (x // 32) * 32 at the USE SITE is what lets Mosaic prove the
        # u8 DMA row offset 32-aligned; an unprovable offset silently takes
        # a ~10x slower DMA path (75 vs 7.5 us per 4096-row chunk, measured)
        at = ((astart + i * ch) // 32) * 32
        pltpu.make_async_copy(
            work_in.at[plane, pl.ds(at, ch), :],
            cin.at[slot], sem.at[slot]).start()

    start_in(0, 0)

    sub_i = jax.lax.broadcasted_iota(i32, (ch, 1), 0)
    iota_sh = jax.lax.broadcasted_iota(i32, (ch, sh), 1)
    jl = jax.lax.broadcasted_iota(i32, (ch, lo_w * nch), 1) // nch

    def word(gb, o):
        # f32 word from 4 u8 bytes; multiplies, not shifts (see above).
        # i32 overflow of the top byte wraps to the sign bits — exactly
        # the bit pattern the bitcast needs.
        return jax.lax.bitcast_convert_type(
            gb[:, o:o + 1] + gb[:, o + 1:o + 2] * 256
            + gb[:, o + 2:o + 3] * 65536
            + gb[:, o + 3:o + 4] * 16777216, f32)

    def body(i, carry):
        slot = jax.lax.rem(i, 2)
        at = ((astart + i * ch) // 32) * 32
        pltpu.make_async_copy(
            work_in.at[plane, pl.ds(at, ch), :],
            cin.at[slot], sem.at[slot]).wait()

        @pl.when(i + 1 < nchunks)
        def _():
            start_in(i + 1, 1 - slot)

        cw = cin[slot].astype(i32)                      # (CH, W)
        bi = cw[:, :F]
        hi = bi // lo_w
        lo = bi - hi * lo_w
        gb = cw[:, F:F + 12]
        pos = sub_i + i * ch
        valid = ((pos >= head) & (pos < tot)).astype(f32)
        g = word(gb, 0) * valid
        h = word(gb, 4) * valid
        c = word(gb, 8) * valid
        if nch == 5:
            g_hi = g.astype(jnp.bfloat16)
            g_lo = (g - g_hi.astype(f32)).astype(jnp.bfloat16)
            h_hi = h.astype(jnp.bfloat16)
            h_lo = (h - h_hi.astype(f32)).astype(jnp.bfloat16)
            chs = jnp.concatenate(
                [g_hi, g_lo, h_hi, h_lo, c.astype(jnp.bfloat16)], axis=1)
        else:
            chs = jnp.concatenate([g, h, c], axis=1).astype(jnp.bfloat16)
        tiled = jnp.concatenate([chs] * lo_w, axis=1)   # (CH, lo_w*nch)

        for f in range(F):
            hioh = (hi[:, f:f + 1] == iota_sh).astype(jnp.bfloat16)
            logf = jnp.where(lo[:, f:f + 1] == jl, tiled,
                             jnp.bfloat16(0))
            ps = jax.lax.dot_general(
                hioh, logf, (((0,), (0,)), ((), ())),
                preferred_element_type=f32)             # (SH, lo_w*nch)
            acc_s[f * sh:(f + 1) * sh, :] += ps
        return carry

    jax.lax.fori_loop(0, nchunks, body, 0)
    out_cp = pltpu.make_async_copy(acc_s, acc_ref, sem.at[0])
    out_cp.start()
    out_cp.wait()


def hist_pallas_segment(work: jax.Array, plane, start, cnt, *,
                        num_bins: int, num_feat: int, exact: bool = True,
                        chunk: int = 4096, lo_w: int = 0):
    """Pallas twin of :func:`hist16_segment` (same contract and the same
    chunk-major f32 accumulation order). Requires the pallas-partition work
    layout: width a multiple of 128, rows start 32-aligned +/- head.

    Returns ``(hist, work)`` — callers MUST continue with the returned work
    buffer: it is byte-identical but aliased through the call, which is what
    keeps XLA from copying the whole buffer defensively per histogram."""
    f = num_feat
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    width = work.shape[2]
    if width % 128:
        raise ValueError("hist_pallas_segment needs 128-lane work rows")
    if chunk % 32:
        # a misaligned chunk silently breaks the (x // 32) * 32 DMA offset
        # re-derivation inside the kernel: rows between the aligned offset
        # and the true chunk start would be double-counted. Refuse loudly;
        # the learner gate (build_kwargs) surfaces this as a config error.
        raise ValueError(
            "hist_pallas_segment chunk must be a multiple of 32 "
            "(u8 sublane DMA tiles), got %d" % chunk)
    kern = partial(_hist_pallas_kernel, ch=chunk, width=width, num_feat=f,
                   sh=sh, lo_w=lo_w, nch=nch)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                   pl.BlockSpec(memory_space=pltpu.HBM)],
        scratch_shapes=[
            pltpu.VMEM((2, chunk, width), jnp.uint8),
            pltpu.VMEM((f * sh, lo_w * nch), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    scalars = jnp.stack([plane.astype(jnp.int32), start.astype(jnp.int32),
                         cnt.astype(jnp.int32)])
    from .partition import _INTERPRET
    work_out, acc = pl.pallas_call(
        kern,
        name="hist_pallas_segment",
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                   jax.ShapeDtypeStruct((f * sh, lo_w * nch), jnp.float32)],
        input_output_aliases={1: 0},
        interpret=_INTERPRET,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(scalars, work)
    h = _hist16_combine(acc.reshape(f, sh, lo_w * nch), num_bins, exact,
                        lo_w)
    return h, work_out


def hist16_segment(work: jax.Array, plane, start, cnt, *,
                   num_bins: int, num_feat: int, exact: bool = True,
                   chunk: int = 2048, lo_w: int = 0) -> jax.Array:
    """Histogram of physical rows [start, start+cnt) of ping-pong plane
    ``plane`` -> (F, num_bins, 3).

    work: (2, Npad, F+12) u8 packed working buffers (ops/partition.py
    pack_rows): bins columns followed by (g, h, cnt) f32 bytes, already
    bagging-masked. plane/start/cnt are traced scalars; one compilation
    serves every leaf.
    """
    from .partition import unpack_ghc

    f = num_feat
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    nchunks = (cnt + chunk - 1) // chunk
    width = work.shape[2]

    def body(i, acc):
        off = start + i * chunk
        cw = jax.lax.dynamic_slice(work, (plane, off, 0),
                                   (1, chunk, width))[0]
        cb = cw[:, :f]
        cg = unpack_ghc(cw, f)
        rows_left = cnt - i * chunk
        valid = jnp.arange(chunk, dtype=jnp.int32) < rows_left
        cgm = cg * valid[:, None].astype(jnp.float32)
        return acc + _hist16_chunk(cb, cgm, num_bins, exact, lo_w)

    # trace_phase: metadata-only op annotation for profiler/HLO attribution
    # (host-side spans refuse to record inside a jit trace)
    with trace_phase("lgbtpu/ops/hist16_segment"):
        acc = jax.lax.fori_loop(
            0, nchunks, body,
            jnp.zeros((f, sh, lo_w * nch), jnp.float32))
        return _hist16_combine(acc, num_bins, exact, lo_w)


# ---------------------------------------------------------------------------
# Planes (feature-major) layout
# ---------------------------------------------------------------------------
#
# Transposed twin of the segment path above for the (2, W, Npad) work
# buffer (ops/partition.py pack_planes): a chunk slice is (W, chunk) —
# each one-hot build reads a CONTIGUOUS per-feature row instead of a
# strided byte column, and rows sit on the 128-lane dim where the VPU
# compares run at full occupancy. Bit-identity with the rows path is a
# hard contract (tests/test_work_layout.py asserts identical trees): same
# chunk boundaries, same lo*nch+ch x-ordering, and the per-chunk einsum
# contracts over the same rows in the same f32 accumulation order — the
# transposed einsum is verified bit-identical on the CPU backend.


def _hist16_chunk_planes(cb, cgm, num_bins: int, exact: bool,
                         lo_w: int = LO_W):
    """(F, C) u8 bin planes + (3, C) f32 masked channel planes ->
    (F, SH, lo_w*NCH) f32. Transposed twin of :func:`_hist16_chunk`."""
    dt = _mxu_dtype()
    sh = (num_bins + lo_w - 1) // lo_w
    hi = (cb >> _LO_SHIFT[lo_w]).astype(jnp.uint8)
    lo = (cb & (lo_w - 1)).astype(jnp.uint8)
    hi_oh = (hi[:, None, :]
             == jnp.arange(sh, dtype=jnp.uint8)[None, :, None]) \
        .astype(dt)                                          # (F, SH, C)
    lo_oh = (lo[:, None, :]
             == jnp.arange(lo_w, dtype=jnp.uint8)[None, :, None])
    if exact:
        g_hi, g_lo = _split_bf16(cgm[0])
        h_hi, h_lo = _split_bf16(cgm[1])
        ch = jnp.stack([g_hi, g_lo, h_hi, h_lo,
                        cgm[2].astype(jnp.bfloat16)], axis=0)  # (5, C)
    else:
        ch = cgm.astype(jnp.bfloat16)                        # (3, C)
    nch = ch.shape[0]
    f, c = cb.shape
    log_ = (lo_oh[:, :, None, :].astype(dt)
            * ch[None, None, :, :].astype(dt)).reshape(f, lo_w * nch, c)
    return jnp.einsum("fhc,fxc->fhx", hi_oh, log_,
                      preferred_element_type=jnp.float32)


def hist16_segment_planes(work: jax.Array, plane, start, cnt, *,
                          num_bins: int, num_feat: int, exact: bool = True,
                          chunk: int = 2048, lo_w: int = 0) -> jax.Array:
    """Planes-layout twin of :func:`hist16_segment` — same contract, work is
    ``(2, W, Npad)`` u8 feature-major planes (ops/partition.py pack_planes):
    bins planes followed by 12 (g, h, cnt) f32-byte planes."""
    from .partition import unpack_ghc_planes

    f = num_feat
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    nchunks = (cnt + chunk - 1) // chunk
    nplanes = work.shape[1]

    def body(i, acc):
        off = start + i * chunk
        cw = jax.lax.dynamic_slice(work, (plane, 0, off),
                                   (1, nplanes, chunk))[0]    # (W, CH)
        cb = cw[:f]
        cg = unpack_ghc_planes(cw, f)                         # (3, CH)
        rows_left = cnt - i * chunk
        valid = jnp.arange(chunk, dtype=jnp.int32) < rows_left
        cgm = cg * valid[None, :].astype(jnp.float32)
        return acc + _hist16_chunk_planes(cb, cgm, num_bins, exact, lo_w)

    with trace_phase("lgbtpu/ops/hist16_segment_planes"):
        acc = jax.lax.fori_loop(
            0, nchunks, body,
            jnp.zeros((f, sh, lo_w * nch), jnp.float32))
        return _hist16_combine(acc, num_bins, exact, lo_w)


def hist16_segment_resident(work: jax.Array, resident: jax.Array, plane,
                            start, cnt, *, num_bins: int, num_feat: int,
                            exact: bool = True, chunk: int = 2048,
                            lo_w: int = 0) -> jax.Array:
    """Resident-state twin of :func:`hist16_segment_planes`.

    ``work`` is the slim (2, W>=17, Npad) buffer (route | ridx x4 | g/h/c
    x12 planes); ``resident`` is the (F, Npad) bin-plane buffer in ORIGINAL
    row order. Per chunk the permuted row-index plane is decoded and the
    bin planes are gathered through it — a unit-stride take along the lane
    axis — reproducing the planes path's leaf-order bin bytes value-for-
    value. Chunk grid, valid masking and _hist16_chunk_planes accumulation
    order are identical, so histograms (and the trees built from them) stay
    bit-identical to ``tpu_work_layout=planes``.
    """
    from .partition import (RST_GH_OFF, RST_ROUTE, RST_WIDTH, _decode_ridx,
                            unpack_ghc_planes)

    f = num_feat
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    nchunks = (cnt + chunk - 1) // chunk
    npad = work.shape[2]

    def body(i, acc):
        off = start + i * chunk
        cw = jax.lax.dynamic_slice(work, (plane, 0, off),
                                   (1, RST_WIDTH, chunk))[0]
        ridx = _decode_ridx(cw[RST_ROUTE:RST_GH_OFF], npad)
        cb = jnp.take(resident, ridx, axis=1)                 # (F, CH)
        cg = unpack_ghc_planes(cw, RST_GH_OFF)                # (3, CH)
        rows_left = cnt - i * chunk
        valid = jnp.arange(chunk, dtype=jnp.int32) < rows_left
        cgm = cg * valid[None, :].astype(jnp.float32)
        return acc + _hist16_chunk_planes(cb, cgm, num_bins, exact, lo_w)

    with trace_phase("lgbtpu/ops/hist16_segment_resident"):
        acc = jax.lax.fori_loop(
            0, nchunks, body,
            jnp.zeros((f, sh, lo_w * nch), jnp.float32))
        return _hist16_combine(acc, num_bins, exact, lo_w)


def _hist_pallas_kernel_planes(sref, work_in, work_ref, acc_ref, cin, acc_s,
                               sem, *, ch, nplanes, num_feat, sh, lo_w, nch,
                               dt):
    # Plane-major port of _hist_pallas_kernel: a chunk DMA is a contiguous
    # (W, ch) lane slice — bins arrive as whole per-feature sublane rows
    # (no strided byte columns) and f32 words re-assemble from 4 byte
    # PLANES instead of 4 byte columns. Same aliasing contract: work_ref is
    # never written, it only keeps the donated buffer from being copied.
    f32 = jnp.float32
    i32 = jnp.int32
    plane = sref[0]
    start = sref[1]
    cnt = sref[2]
    F = num_feat

    astart = (start // 128) * 128
    head = start - astart
    tot = head + cnt
    nchunks = jnp.maximum((tot + ch - 1) // ch, 1)

    acc_s[...] = jnp.zeros((F * sh, lo_w * nch), f32)

    def start_in(i, slot):
        # (x // 128) * 128 at the USE SITE proves the u8 lane-dim DMA
        # offset is whole 128-lane tiles (the planes twin of the rows
        # kernel's 32-row sublane alignment)
        at = ((astart + i * ch) // 128) * 128
        pltpu.make_async_copy(
            work_in.at[plane, :, pl.ds(at, ch)],
            cin.at[slot], sem.at[slot]).start()

    start_in(0, 0)

    lane_i = jax.lax.broadcasted_iota(i32, (1, ch), 1)
    iota_sh = jax.lax.broadcasted_iota(i32, (sh, ch), 0)
    jl = jax.lax.broadcasted_iota(i32, (lo_w * nch, ch), 0) // nch

    def word(gb, o):
        # f32 plane from its 4 u8 byte planes; multiplies, not shifts
        # (vector << by >= 16 miscompiles on this toolchain — see the rows
        # kernel). i32 overflow of the top byte wraps to the sign bits.
        return jax.lax.bitcast_convert_type(
            gb[o:o + 1] + gb[o + 1:o + 2] * 256
            + gb[o + 2:o + 3] * 65536
            + gb[o + 3:o + 4] * 16777216, f32)

    def body(i, carry):
        slot = jax.lax.rem(i, 2)
        at = ((astart + i * ch) // 128) * 128
        pltpu.make_async_copy(
            work_in.at[plane, :, pl.ds(at, ch)],
            cin.at[slot], sem.at[slot]).wait()

        @pl.when(i + 1 < nchunks)
        def _():
            start_in(i + 1, 1 - slot)

        cw = cin[slot].astype(i32)                      # (W, CH)
        bi = cw[:F]
        hi = bi // lo_w
        lo = bi - hi * lo_w
        gb = cw[F:F + 12]
        pos = lane_i + i * ch
        valid = ((pos >= head) & (pos < tot)).astype(f32)
        g = word(gb, 0) * valid
        h = word(gb, 4) * valid
        c = word(gb, 8) * valid
        if nch == 5:
            g_hi = g.astype(jnp.bfloat16)
            g_lo = (g - g_hi.astype(f32)).astype(jnp.bfloat16)
            h_hi = h.astype(jnp.bfloat16)
            h_lo = (h - h_hi.astype(f32)).astype(jnp.bfloat16)
            chs = jnp.concatenate(
                [g_hi, g_lo, h_hi, h_lo, c.astype(jnp.bfloat16)], axis=0)
        else:
            chs = jnp.concatenate([g, h, c], axis=0).astype(jnp.bfloat16)
        tiled = jnp.concatenate([chs] * lo_w, axis=0).astype(dt)

        for f in range(F):
            hioh = (hi[f:f + 1] == iota_sh).astype(dt)  # (SH, CH)
            logf = jnp.where(lo[f:f + 1] == jl, tiled,
                             jnp.zeros((), dt))         # (lo_w*nch, CH)
            ps = jax.lax.dot_general(
                hioh, logf, (((1,), (1,)), ((), ())),
                preferred_element_type=f32)             # (SH, lo_w*nch)
            acc_s[f * sh:(f + 1) * sh, :] += ps
        return carry

    jax.lax.fori_loop(0, nchunks, body, 0)
    out_cp = pltpu.make_async_copy(acc_s, acc_ref, sem.at[0])
    out_cp.start()
    out_cp.wait()


def hist_pallas_segment_planes(work: jax.Array, plane, start, cnt, *,
                               num_bins: int, num_feat: int,
                               exact: bool = True, chunk: int = 4096,
                               lo_w: int = 0):
    """Pallas twin of :func:`hist16_segment_planes` for the (2, W, Npad)
    plane-major work buffer. Requires the planes pallas work layout: W a
    multiple of 32 sublanes, lane starts 128-aligned +/- head, chunk a
    multiple of 128.

    Returns ``(hist, work)`` — same aliasing contract as
    :func:`hist_pallas_segment`. Runs under the pallas interpreter off-TPU
    (LGBTPU_PALLAS_INTERPRET=1) with f32 operands so the parity test can
    compare against the exact XLA path.
    """
    from .partition import _INTERPRET

    f = num_feat
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 5 if exact else 3
    nplanes = work.shape[1]
    if nplanes % 32:
        raise ValueError(
            "hist_pallas_segment_planes needs whole 32-sublane u8 plane "
            "tiles, got W=%d" % nplanes)
    if chunk % 128:
        # a misaligned chunk breaks the (x // 128) * 128 lane-offset
        # re-derivation inside the kernel (lanes between the aligned offset
        # and the true chunk start would be double-counted)
        raise ValueError(
            "hist_pallas_segment_planes chunk must be a multiple of 128 "
            "(lane DMA tiles), got %d" % chunk)
    kern = partial(_hist_pallas_kernel_planes, ch=chunk, nplanes=nplanes,
                   num_feat=f, sh=sh, lo_w=lo_w, nch=nch, dt=_mxu_dtype())
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                   pl.BlockSpec(memory_space=pltpu.HBM)],
        scratch_shapes=[
            pltpu.VMEM((2, nplanes, chunk), jnp.uint8),
            pltpu.VMEM((f * sh, lo_w * nch), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    scalars = jnp.stack([plane.astype(jnp.int32), start.astype(jnp.int32),
                         cnt.astype(jnp.int32)])
    work_out, acc = pl.pallas_call(
        kern,
        name="hist_pallas_segment_planes",
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                   jax.ShapeDtypeStruct((f * sh, lo_w * nch), jnp.float32)],
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(scalars, work)
    h = _hist16_combine(acc.reshape(f, sh, lo_w * nch), num_bins, exact,
                        lo_w)
    return h, work_out


# ---------------------------------------------------------------------------
# One-hot MXU histogram (rows layout, f32-hilo + int8 from one body)
# ---------------------------------------------------------------------------
#
# The rows pallas kernel above already keeps the accumulator in VMEM, but
# its per-feature dots carry bf16 operands only — the use_quantized_grad
# int8 path still falls back to the XLA einsum loop (hist16_segment_q),
# which XLA will not lower to the MXU (PERF round 3: the int8 batched
# einsum stays on the VPU, projected ~39 -> ~15-20 ms/iter if it fed the
# MXU). This kernel serves BOTH precisions from one body: the one-hots
# build in VMEM per chunk and feed the MXU via jax.lax.dot_general —
# bf16 x bf16 -> f32 for the hi/lo-16 path (identical channel math to
# _hist_pallas_kernel) and int8 x int8 -> i32 for quantized training
# (2x bf16 MXU peak, and integer accumulation is order-exact, so parity
# with hist16_segment_q holds bit-for-bit at ANY chunk grouping). Unlike
# hist_pallas_segment it runs under the pallas interpreter off-TPU with
# f32 operands (the planes kernel's precedent), so the parity tests pin
# both modes against the XLA oracles on CPU.


def _hist_mxu_kernel(sref, work_in, work_ref, acc_ref, cin, acc_s, sem,
                     *, ch, width, num_feat, sh, lo_w, nch, quantized, dt):
    # same aliasing contract as _hist_pallas_kernel: work_ref is never
    # written — it exists so the donated work buffer aliases through the
    # call instead of being defensively copied per histogram
    f32 = jnp.float32
    i32 = jnp.int32
    plane = sref[0]
    start = sref[1]
    cnt = sref[2]
    F = num_feat

    astart = (start // 32) * 32
    head = start - astart
    tot = head + cnt
    nchunks = jnp.maximum((tot + ch - 1) // ch, 1)
    acc_dt = i32 if quantized else f32
    odt = jnp.int8 if quantized else dt

    acc_s[...] = jnp.zeros((F * sh, lo_w * nch), acc_dt)

    def start_in(i, slot):
        # (x // 32) * 32 at the USE SITE proves the u8 DMA row offset
        # 32-aligned (see _hist_pallas_kernel)
        at = ((astart + i * ch) // 32) * 32
        pltpu.make_async_copy(
            work_in.at[plane, pl.ds(at, ch), :],
            cin.at[slot], sem.at[slot]).start()

    start_in(0, 0)

    sub_i = jax.lax.broadcasted_iota(i32, (ch, 1), 0)
    iota_sh = jax.lax.broadcasted_iota(i32, (ch, sh), 1)
    jl = jax.lax.broadcasted_iota(i32, (ch, lo_w * nch), 1) // nch

    def word(gb, o):
        # f32 word from 4 u8 bytes; multiplies, not shifts (vector << by
        # >= 16 miscompiles on this toolchain — see _hist_pallas_kernel)
        return jax.lax.bitcast_convert_type(
            gb[:, o:o + 1] + gb[:, o + 1:o + 2] * 256
            + gb[:, o + 2:o + 3] * 65536
            + gb[:, o + 3:o + 4] * 16777216, f32)

    def body(i, carry):
        slot = jax.lax.rem(i, 2)
        at = ((astart + i * ch) // 32) * 32
        pltpu.make_async_copy(
            work_in.at[plane, pl.ds(at, ch), :],
            cin.at[slot], sem.at[slot]).wait()

        @pl.when(i + 1 < nchunks)
        def _():
            start_in(i + 1, 1 - slot)

        cw = cin[slot].astype(i32)                      # (CH, W)
        bi = cw[:, :F]
        hi = bi // lo_w
        lo = bi - hi * lo_w
        pos = sub_i + i * ch
        vb = (pos >= head) & (pos < tot)
        if quantized:
            # (F+3) u8 rows: bins | int8 g byte | int8 h byte | u8 cnt
            # (pack_rows_quantized). Sign-decode from the u8-as-i32 view;
            # the valid mask multiplies in as an exact integer 0/1.
            gq = cw[:, F:F + 1]
            gq = jnp.where(gq >= 128, gq - 256, gq)
            hq = cw[:, F + 1:F + 2]
            hq = jnp.where(hq >= 128, hq - 256, hq)
            cq = cw[:, F + 2:F + 3]
            v = vb.astype(i32)
            tiled = jnp.concatenate(
                [jnp.concatenate([gq * v, hq * v, cq * v], axis=1)
                 .astype(jnp.int8)] * lo_w, axis=1)     # (CH, lo_w*3)
        else:
            gb = cw[:, F:F + 12]
            valid = vb.astype(f32)
            g = word(gb, 0) * valid
            h = word(gb, 4) * valid
            c = word(gb, 8) * valid
            if nch == 5:
                g_hi = g.astype(jnp.bfloat16)
                g_lo = (g - g_hi.astype(f32)).astype(jnp.bfloat16)
                h_hi = h.astype(jnp.bfloat16)
                h_lo = (h - h_hi.astype(f32)).astype(jnp.bfloat16)
                chs = jnp.concatenate(
                    [g_hi, g_lo, h_hi, h_lo, c.astype(jnp.bfloat16)],
                    axis=1)
            else:
                chs = jnp.concatenate([g, h, c], axis=1) \
                    .astype(jnp.bfloat16)
            tiled = jnp.concatenate([chs] * lo_w, axis=1).astype(dt)

        for f in range(F):
            hioh = (hi[:, f:f + 1] == iota_sh).astype(odt)
            logf = jnp.where(lo[:, f:f + 1] == jl, tiled,
                             jnp.zeros((), odt))
            ps = jax.lax.dot_general(
                hioh, logf, (((0,), (0,)), ((), ())),
                preferred_element_type=acc_dt)          # (SH, lo_w*nch)
            acc_s[f * sh:(f + 1) * sh, :] += ps
        return carry

    jax.lax.fori_loop(0, nchunks, body, 0)
    out_cp = pltpu.make_async_copy(acc_s, acc_ref, sem.at[0])
    out_cp.start()
    out_cp.wait()


def hist_mxu_segment(work: jax.Array, plane, start, cnt, *,
                     num_bins: int, num_feat: int, quantized: bool = False,
                     gscale=None, hscale=None, exact: bool = True,
                     chunk: int = 4096, lo_w: int = 0):
    """One-hot MXU segment histogram -> ``(hist (F, num_bins, 3) f32, work)``.

    Serves two precisions from one kernel body (``tpu_hist_mxu``):

    - ``quantized=False``: f32 hi/lo-16 — same channel math and chunk
      accumulation as :func:`hist_pallas_segment`, bit-parity oracle
      :func:`hist16_segment`.
    - ``quantized=True``: int8 one-hots x int8 channels -> i32 MXU
      accumulation, dequantized with ``gscale``/``hscale`` exactly as
      :func:`hist16_segment_q` (the oracle) — integer accumulation makes
      the parity exact at any chunk size or segment alignment.

    Requires the rows pallas work layout: width a multiple of 128, chunk a
    multiple of 32. Same aliasing contract as :func:`hist_pallas_segment`
    (callers must continue with the returned work buffer). Runs under the
    pallas interpreter off-TPU (LGBTPU_PALLAS_INTERPRET=1) with f32
    operands so parity tests compare against the exact XLA paths.
    """
    from .partition import _INTERPRET

    f = num_feat
    lo_w = lo_w or auto_lo_w(f)
    sh = (num_bins + lo_w - 1) // lo_w
    nch = 3 if quantized else (5 if exact else 3)
    width = work.shape[2]
    if width % 128:
        raise ValueError("hist_mxu_segment needs 128-lane work rows")
    if chunk % 32:
        raise ValueError(
            "hist_mxu_segment chunk must be a multiple of 32 "
            "(u8 sublane DMA tiles), got %d" % chunk)
    if quantized and (gscale is None or hscale is None):
        raise ValueError("hist_mxu_segment quantized mode needs gscale/hscale")
    acc_dt = jnp.int32 if quantized else jnp.float32
    kern = partial(_hist_mxu_kernel, ch=chunk, width=width, num_feat=f,
                   sh=sh, lo_w=lo_w, nch=nch, quantized=quantized,
                   dt=_mxu_dtype())
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                   pl.BlockSpec(memory_space=pltpu.HBM)],
        scratch_shapes=[
            pltpu.VMEM((2, chunk, width), jnp.uint8),
            pltpu.VMEM((f * sh, lo_w * nch), acc_dt),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    scalars = jnp.stack([plane.astype(jnp.int32), start.astype(jnp.int32),
                         cnt.astype(jnp.int32)])
    work_out, acc = pl.pallas_call(
        kern,
        name="hist_mxu_segment",
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                   jax.ShapeDtypeStruct((f * sh, lo_w * nch), acc_dt)],
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_INTERPRET,
    )(scalars, work)
    if quantized:
        h = acc.reshape(f, sh, lo_w, 3).reshape(f, sh * lo_w, 3)[:, :num_bins]
        scale = jnp.stack([1.0 / gscale, 1.0 / hscale, jnp.float32(1.0)])
        return h.astype(jnp.float32) * scale[None, None, :], work_out
    h = _hist16_combine(acc.reshape(f, sh, lo_w * nch), num_bins, exact,
                        lo_w)
    return h, work_out
