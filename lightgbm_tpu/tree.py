"""Leaf-wise decision tree model: fixed-capacity arrays + prediction.

TPU-native equivalent of the reference ``Tree`` (reference:
include/LightGBM/tree.h:25, src/io/tree.cpp). Differences by design:

- Trees are *built on device* by the jitted learner as a flat "split log"
  (one record per split round); this class reconstructs the standard
  internal-node/leaf structure on host for prediction and serialization.
- Prediction over a batch of rows is vectorized (numpy on host, and the
  learner routes binned rows on device with per-split bin tables), instead
  of the reference's per-row pointer walk (tree.h:133 NumericalDecision).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# decision_type bit layout (reference: include/LightGBM/tree.h:149-166)
K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
# missing type stored in bits 2-3 (values 0=None,1=Zero,2=NaN)


class Tree:
    """A fitted decision tree with ``num_leaves`` leaves.

    Internal node ``i`` (0-based, creation order) holds a split; children are
    node indices where negative values encode leaves: leaf ``j`` is stored as
    ``~j`` (reference: tree.h left_child_/right_child_ convention).
    """

    def __init__(self, num_leaves: int, has_categorical: bool = False) -> None:
        n = max(num_leaves - 1, 1)
        self.num_leaves = num_leaves
        self.split_feature: np.ndarray = np.zeros(n, dtype=np.int32)  # real feature idx
        self.split_bin: np.ndarray = np.zeros(n, dtype=np.int32)
        self.threshold: np.ndarray = np.zeros(n, dtype=np.float64)
        self.decision_type: np.ndarray = np.zeros(n, dtype=np.int8)
        self.left_child: np.ndarray = np.full(n, -1, dtype=np.int32)
        self.right_child: np.ndarray = np.full(n, -1, dtype=np.int32)
        self.split_gain: np.ndarray = np.zeros(n, dtype=np.float32)
        self.internal_value: np.ndarray = np.zeros(n, dtype=np.float64)
        self.internal_weight: np.ndarray = np.zeros(n, dtype=np.float64)
        self.internal_count: np.ndarray = np.zeros(n, dtype=np.int64)
        self.leaf_value: np.ndarray = np.zeros(num_leaves, dtype=np.float64)
        self.leaf_weight: np.ndarray = np.zeros(num_leaves, dtype=np.float64)
        self.leaf_count: np.ndarray = np.zeros(num_leaves, dtype=np.int64)
        self.leaf_parent: np.ndarray = np.full(num_leaves, -1, dtype=np.int32)
        # categorical split i -> sorted array of category values going LEFT
        self.cat_threshold: Dict[int, np.ndarray] = {}
        self.shrinkage: float = 1.0
        self.num_cat: int = 0
        # linear leaves (reference: tree.h leaf_const_/leaf_coeff_/
        # leaf_features_, fit by LinearTreeLearner::CalculateLinear)
        self.is_linear: bool = False
        self.leaf_const: np.ndarray = np.zeros(num_leaves, dtype=np.float64)
        self.leaf_coeff: Dict[int, np.ndarray] = {}
        self.leaf_features: Dict[int, np.ndarray] = {}  # real feature idx

    # ------------------------------------------------------------------ build
    @classmethod
    def from_split_log(
        cls,
        num_splits: int,
        split_leaf: np.ndarray,      # (R,) leaf index split at round r
        split_feature: np.ndarray,   # (R,) inner feature index
        split_bin: np.ndarray,       # (R,) threshold bin
        default_left: np.ndarray,    # (R,) bool
        split_gain: np.ndarray,      # (R,)
        left_sum: np.ndarray,        # (R, 3) g,h,cnt of left child at split time
        right_sum: np.ndarray,       # (R, 3)
        leaf_value: np.ndarray,      # (num_leaves,) final leaf outputs
        *,
        bin_mappers: Sequence[Any],
        real_feature_index: Sequence[int],
        go_left_table: Optional[np.ndarray] = None,  # (R, B) bool, categorical splits
        is_categorical: Optional[np.ndarray] = None,  # (R,) bool
    ) -> "Tree":
        """Rebuild the node structure from the learner's split log.

        Round ``r`` splits leaf ``l``: internal node ``r`` is created, the left
        child keeps leaf index ``l`` and the right child becomes leaf ``r+1``
        (reference: Tree::Split semantics, tree.h:62 — same leaf-index reuse).
        """
        num_leaves = num_splits + 1
        t = cls(num_leaves)
        # leaf -> node currently representing it (-1 while it is the root)
        leaf_slot: Dict[int, tuple] = {0: (-1, 0)}  # leaf -> (parent node, side 0=L 1=R)
        for r in range(num_splits):
            l = int(split_leaf[r])
            inner_f = int(split_feature[r])
            mapper = bin_mappers[inner_f]
            t.split_feature[r] = int(real_feature_index[inner_f])
            t.split_bin[r] = int(split_bin[r])
            dtyp = 0
            if is_categorical is not None and bool(is_categorical[r]):
                dtyp |= K_CATEGORICAL_MASK
                t.num_cat += 1
                # table row -> real category values going left
                tbl = go_left_table[r, : mapper.num_bins]
                bins_left = np.flatnonzero(tbl)
                cats = [mapper.bin_to_value(int(b)) for b in bins_left
                        if b < len(mapper.categories)]
                t.cat_threshold[r] = np.asarray(sorted(int(c) for c in cats), dtype=np.int64)
                t.threshold[r] = float(len(t.cat_threshold))  # placeholder index-ish
            else:
                if bool(default_left[r]):
                    dtyp |= K_DEFAULT_LEFT_MASK
                dtyp |= (mapper.missing_type & 3) << 2
                t.threshold[r] = mapper.bin_to_value(int(split_bin[r]))
            t.decision_type[r] = dtyp
            t.split_gain[r] = float(split_gain[r])
            gl, hl, cl = (float(left_sum[r, 0]), float(left_sum[r, 1]), float(left_sum[r, 2]))
            gr, hr, cr = (float(right_sum[r, 0]), float(right_sum[r, 1]), float(right_sum[r, 2]))
            t.internal_weight[r] = hl + hr
            t.internal_count[r] = int(round(cl + cr))
            tot_h = hl + hr
            t.internal_value[r] = -(gl + gr) / tot_h if tot_h > 0 else 0.0
            # hook up parent pointer
            parent, side = leaf_slot.pop(l)
            if parent >= 0:
                if side == 0:
                    t.left_child[parent] = r
                else:
                    t.right_child[parent] = r
            new_leaf = r + 1
            t.left_child[r] = ~l
            t.right_child[r] = ~new_leaf
            t.leaf_parent[l] = r
            t.leaf_parent[new_leaf] = r
            t.leaf_weight[l], t.leaf_count[l] = hl, int(round(cl))
            t.leaf_weight[new_leaf], t.leaf_count[new_leaf] = hr, int(round(cr))
            leaf_slot[l] = (r, 0)
            leaf_slot[new_leaf] = (r, 1)
        if num_splits == 0:
            # no usable split: the tree contributes nothing (reference:
            # gbdt.cpp keeps the stump but never applies its output)
            return t
        t.leaf_value[:num_leaves] = np.asarray(leaf_value[:num_leaves], dtype=np.float64)
        return t

    # ---------------------------------------------------------------- predict
    def _decide(self, node: int, values: np.ndarray) -> np.ndarray:
        """Vectorized left/right decision for internal node over raw values.

        Mirrors reference NumericalDecision / CategoricalDecision
        (tree.h:133-166): missing handling None (NaN->0), Zero (NaN->0 and
        |x|<=kZeroThreshold routed to the default direction), NaN (default
        dir). Returns bool array: True -> go left.
        """
        dt = int(self.decision_type[node])
        if dt & K_CATEGORICAL_MASK:
            cats = self.cat_threshold.get(node, np.array([], dtype=np.int64))
            iv = np.where(np.isfinite(values), values, -1).astype(np.int64)
            return np.isin(iv, cats)
        thr = self.threshold[node]
        missing_type = (dt >> 2) & 3
        default_left = bool(dt & K_DEFAULT_LEFT_MASK)
        nan_mask = np.isnan(values)
        if missing_type == 2:  # NaN-aware
            base = values <= thr
            return np.where(nan_mask, default_left, base)
        # None/Zero: NaN behaves as 0 (reference tree.h NumericalDecision)
        v = np.where(nan_mask, 0.0, values)
        base = v <= thr
        if missing_type == 1:  # zero as missing: zeros take the default dir
            return np.where(np.abs(v) <= 1e-35, default_left, base)
        return base

    def to_if_else(self, index: int) -> str:
        """Emit this tree as a standalone C++ if-else function
        (reference: gbdt_model_text.cpp:258 GBDT::ModelToIfElse — the
        reference also uses the generated code as a prediction regression
        harness; tests/test_codegen.py does the same here).

        Decision semantics mirror _decide: None/Zero missing treats NaN as
        0.0 (Zero additionally routes |x|<=1e-35 to the default side);
        NaN-aware splits route NaN to the default side. Linear leaves emit
        their const + coeffs . x model guarded by the NaN fallback to the
        plain leaf value (linear_predict semantics).
        """
        lines = ["double PredictTree%d(const double* arr) {" % index]
        if self.num_leaves <= 1:
            const0 = self.leaf_const[0] if self.is_linear \
                else self.leaf_value[0]
            lines.append("  return %.17g;" % float(const0))
            lines.append("}")
            return "\n".join(lines)

        def emit_leaf(leaf: int, ind: str, out):
            if not self.is_linear:
                out.append("%sreturn %.17g;"
                           % (ind, float(self.leaf_value[leaf])))
                return
            feats = self.leaf_features.get(leaf)
            if feats is None or len(feats) == 0:
                out.append("%sreturn %.17g;"
                           % (ind, float(self.leaf_const[leaf])))
                return
            # any NaN among the leaf's features -> plain leaf value
            nan_check = " || ".join("std::isnan(arr[%d])" % int(f)
                                    for f in feats)
            terms = " + ".join(
                "%.17g * arr[%d]" % (float(c), int(f))
                for f, c in zip(feats, self.leaf_coeff[leaf]))
            out.append("%sif (%s) return %.17g;"
                       % (ind, nan_check, float(self.leaf_value[leaf])))
            out.append("%sreturn %.17g + %s;"
                       % (ind, float(self.leaf_const[leaf]), terms))

        def emit(node: int, ind: str, out):
            if node < 0:
                emit_leaf(~node, ind, out)
                return
            f = int(self.split_feature[node])
            dt = int(self.decision_type[node])
            if dt & K_CATEGORICAL_MASK:
                cats = self.cat_threshold.get(
                    node, np.array([], dtype=np.int64))
                cond = ("!std::isnan(arr[%d]) && cat_in((int64_t)arr[%d], "
                        "kCats%d_%d, %d)" % (f, f, index, node, len(cats)))
                out.append("%sif (%s) {" % (ind, cond))
            else:
                thr = float(self.threshold[node])
                missing_type = (dt >> 2) & 3
                default_left = bool(dt & K_DEFAULT_LEFT_MASK)
                if missing_type == 2:
                    cond = "std::isnan(arr[%d]) ? %s : (arr[%d] <= %.17g)" \
                        % (f, "true" if default_left else "false", f, thr)
                elif missing_type == 1:
                    cond = ("[&]{ double v = std::isnan(arr[%d]) ? 0.0 : "
                            "arr[%d]; return std::fabs(v) <= 1e-35 ? %s : "
                            "(v <= %.17g); }()"
                            % (f, f, "true" if default_left else "false",
                               thr))
                else:
                    cond = ("(std::isnan(arr[%d]) ? 0.0 : arr[%d]) <= %.17g"
                            % (f, f, thr))
                out.append("%sif (%s) {" % (ind, cond))
            emit(int(self.left_child[node]), ind + "  ", out)
            out.append("%s} else {" % ind)
            emit(int(self.right_child[node]), ind + "  ", out)
            out.append("%s}" % ind)

        # category tables for this tree
        pre = []
        for node, cats in sorted(self.cat_threshold.items()):
            pre.append("static const int64_t kCats%d_%d[%d] = {%s};"
                       % (index, node, max(len(cats), 1),
                          ", ".join(str(int(c)) for c in cats) or "0"))
        body: list = []
        emit(0, "  ", body)
        return "\n".join(pre + lines + body + ["}"])

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Batch prediction of leaf outputs for raw feature rows."""
        X = np.asarray(X, dtype=np.float64)
        n = X.shape[0]
        if self.num_leaves <= 1:
            if self.is_linear:
                return np.full(n, self.leaf_const[0], dtype=np.float64)
            return np.full(n, self.leaf_value[0], dtype=np.float64)
        if self.is_linear:
            return self.linear_predict(X, self.predict_leaf_index(X))
        node = np.zeros(n, dtype=np.int64)  # >=0 internal, <0 leaf (~leaf)
        active = node >= 0
        while np.any(active):
            for nd in np.unique(node[active]):
                sel = active & (node == nd)
                go_left = self._decide(int(nd), X[sel, self.split_feature[nd]])
                nxt = np.where(go_left, self.left_child[nd], self.right_child[nd])
                node[sel] = nxt
            active = node >= 0
        return self.leaf_value[~node]

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float64)
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int64)
        active = node >= 0
        while np.any(active):
            for nd in np.unique(node[active]):
                sel = active & (node == nd)
                go_left = self._decide(int(nd), X[sel, self.split_feature[nd]])
                node[sel] = np.where(go_left, self.left_child[nd], self.right_child[nd])
            active = node >= 0
        return (~node).astype(np.int32)

    def to_split_arrays(self) -> Dict[str, np.ndarray]:
        """Flatten the node structure into leaf-slot split order.

        Returns per-split arrays usable by the device routers: processing
        split r, rows on slot ``slot[r]`` move to slot ``r+1`` unless the
        decision sends them left (the same slot-reuse convention as the
        learner's TreeLog; from_split_log's inverse). Works for any tree —
        including models loaded from reference-format text.
        """
        R = self.num_internal if self.num_leaves > 1 else 0
        slot = np.zeros(R, np.int32)
        feature = np.zeros(R, np.int32)
        threshold = np.zeros(R, np.float64)
        kind = np.zeros(R, np.int32)          # 0 numerical, 1 categorical
        default_left = np.zeros(R, bool)
        missing_type = np.zeros(R, np.int32)
        cat_values: Dict[int, np.ndarray] = {}
        leaf_of_slot = np.zeros(max(self.num_leaves, 1), np.int32)
        if R == 0:
            leaf_of_slot[0] = 0
            return dict(slot=slot, feature=feature, threshold=threshold,
                        kind=kind, default_left=default_left,
                        missing_type=missing_type, cat_values=cat_values,
                        leaf_of_slot=leaf_of_slot)
        # BFS from the root; order = our split order r; slots assigned on
        # the fly (left keeps the parent's slot, right takes slot r+1)
        order: List[int] = []
        node_slot = {0: 0}
        queue = [0]
        while queue:
            nd = queue.pop(0)
            r = len(order)
            order.append(nd)
            s = node_slot.pop(nd)
            slot[r] = s
            feature[r] = self.split_feature[nd]
            threshold[r] = self.threshold[nd]
            dt = int(self.decision_type[nd])
            kind[r] = 1 if dt & K_CATEGORICAL_MASK else 0
            default_left[r] = bool(dt & K_DEFAULT_LEFT_MASK)
            missing_type[r] = (dt >> 2) & 3
            if kind[r]:
                cat_values[r] = self.cat_threshold.get(
                    nd, np.array([], dtype=np.int64))
            for child, child_slot in ((self.left_child[nd], s),
                                      (self.right_child[nd], r + 1)):
                if child >= 0:
                    node_slot[int(child)] = child_slot
                    queue.append(int(child))
                else:
                    leaf_of_slot[child_slot] = ~child
        # BFS guarantees parents precede children, but the right-child slot
        # r+1 refers to THIS split's position — valid since rows can only
        # reach a child's test after the parent's test ran
        return dict(slot=slot, feature=feature, threshold=threshold,
                    kind=kind, default_left=default_left,
                    missing_type=missing_type, cat_values=cat_values,
                    leaf_of_slot=leaf_of_slot)

    def branch_features(self, leaf: int) -> np.ndarray:
        """Real feature indices on the path from the root to ``leaf``
        (reference: tree.h branch_features with track_branch_features)."""
        feats = []
        nd = int(self.leaf_parent[leaf])
        seen = set()
        while nd >= 0:
            f = int(self.split_feature[nd])
            if f not in seen:
                seen.add(f)
                feats.append(f)
            # walk up: find the parent of nd
            up = np.flatnonzero((self.left_child == nd) | (self.right_child == nd))
            nd = int(up[0]) if len(up) else -1
        return np.asarray(sorted(feats), dtype=np.int64)

    def linear_predict(self, X: np.ndarray, leaf: np.ndarray) -> np.ndarray:
        """Linear-leaf outputs for rows routed to ``leaf`` (reference:
        tree.h:127 — const + coeffs . x; rows with NaN in any used feature
        fall back to the plain leaf value)."""
        out = self.leaf_const[leaf].copy()
        for l in range(self.num_leaves):
            m = leaf == l
            if not np.any(m):
                continue
            feats = self.leaf_features.get(l)
            if feats is None or len(feats) == 0:
                continue
            Z = X[np.ix_(m, feats)]
            nan_rows = np.isnan(Z).any(axis=1)
            vals = self.leaf_const[l] + Z @ self.leaf_coeff[l]
            vals = np.where(nan_rows, self.leaf_value[l], vals)
            out[m] = vals
        return out

    def apply_shrinkage(self, rate: float) -> None:
        """(reference: tree.h:187 Shrinkage — scales linear leaves too)"""
        self.leaf_value *= rate
        self.internal_value *= rate
        self.shrinkage *= rate
        if self.is_linear:
            self.leaf_const *= rate
            for l in self.leaf_coeff:
                self.leaf_coeff[l] = self.leaf_coeff[l] * rate

    def add_bias(self, val: float) -> None:
        self.leaf_value += val
        self.internal_value += val

    @property
    def num_internal(self) -> int:
        return self.num_leaves - 1

    def leaf_depths(self) -> np.ndarray:
        depth = np.zeros(self.num_leaves, dtype=np.int32)
        if self.num_leaves <= 1:
            return depth
        node_depth = np.zeros(self.num_internal, dtype=np.int32)
        for r in range(self.num_internal):
            for child in (self.left_child[r], self.right_child[r]):
                if child >= 0:
                    node_depth[child] = node_depth[r] + 1
                else:
                    depth[~child] = node_depth[r] + 1
        return depth

    # -------------------------------------------------------------- serialize
    def to_dict(self) -> Dict[str, Any]:
        d = {
            "num_leaves": int(self.num_leaves),
            "num_cat": int(self.num_cat),
            "shrinkage": float(self.shrinkage),
            "leaf_value": self.leaf_value.tolist(),
            "leaf_weight": self.leaf_weight.tolist(),
            "leaf_count": self.leaf_count.tolist(),
        }
        if self.num_leaves > 1:
            d.update({
                "split_feature": self.split_feature.tolist(),
                "split_gain": self.split_gain.tolist(),
                "threshold": self.threshold.tolist(),
                "decision_type": self.decision_type.tolist(),
                "left_child": self.left_child.tolist(),
                "right_child": self.right_child.tolist(),
                "internal_value": self.internal_value.tolist(),
                "internal_weight": self.internal_weight.tolist(),
                "internal_count": self.internal_count.tolist(),
                "cat_threshold": {str(k): v.tolist() for k, v in self.cat_threshold.items()},
            })
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Tree":
        t = cls(int(d["num_leaves"]))
        t.shrinkage = float(d.get("shrinkage", 1.0))
        t.num_cat = int(d.get("num_cat", 0))
        t.leaf_value = np.asarray(d["leaf_value"], dtype=np.float64)
        t.leaf_weight = np.asarray(d.get("leaf_weight", np.zeros(t.num_leaves)), dtype=np.float64)
        t.leaf_count = np.asarray(d.get("leaf_count", np.zeros(t.num_leaves)), dtype=np.int64)
        if t.num_leaves > 1:
            t.split_feature = np.asarray(d["split_feature"], dtype=np.int32)
            t.split_gain = np.asarray(d["split_gain"], dtype=np.float32)
            t.threshold = np.asarray(d["threshold"], dtype=np.float64)
            t.decision_type = np.asarray(d["decision_type"], dtype=np.int8)
            t.left_child = np.asarray(d["left_child"], dtype=np.int32)
            t.right_child = np.asarray(d["right_child"], dtype=np.int32)
            t.internal_value = np.asarray(d["internal_value"], dtype=np.float64)
            t.internal_weight = np.asarray(d["internal_weight"], dtype=np.float64)
            t.internal_count = np.asarray(d["internal_count"], dtype=np.int64)
            t.cat_threshold = {int(k): np.asarray(v, dtype=np.int64)
                               for k, v in d.get("cat_threshold", {}).items()}
        return t

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def to_text(self) -> str:
        """Text block in the spirit of the reference model format
        (reference: src/boosting/gbdt_model_text.cpp:311 ``Tree=N`` blocks)."""
        lines = [
            "num_leaves=%d" % self.num_leaves,
            "num_cat=%d" % self.num_cat,
            "shrinkage=%g" % self.shrinkage,
            "leaf_value=" + " ".join("%.17g" % v for v in self.leaf_value),
            "leaf_weight=" + " ".join("%g" % v for v in self.leaf_weight),
            "leaf_count=" + " ".join(str(int(v)) for v in self.leaf_count),
        ]
        if self.num_leaves > 1:
            lines += [
                "split_feature=" + " ".join(str(v) for v in self.split_feature),
                "split_gain=" + " ".join("%g" % v for v in self.split_gain),
                "threshold=" + " ".join("%.17g" % v for v in self.threshold),
                "decision_type=" + " ".join(str(int(v)) for v in self.decision_type),
                "left_child=" + " ".join(str(v) for v in self.left_child),
                "right_child=" + " ".join(str(v) for v in self.right_child),
                "internal_value=" + " ".join("%g" % v for v in self.internal_value),
                "internal_weight=" + " ".join("%g" % v for v in self.internal_weight),
                "internal_count=" + " ".join(str(int(v)) for v in self.internal_count),
            ]
            if self.cat_threshold:
                cat_items = ["%d:%s" % (k, ",".join(str(c) for c in v))
                             for k, v in sorted(self.cat_threshold.items())]
                lines.append("cat_threshold=" + ";".join(cat_items))
        if self.is_linear:
            nf = [len(self.leaf_features.get(l, ())) for l in range(self.num_leaves)]
            feats, coefs = [], []
            for l in range(self.num_leaves):
                feats.extend(int(f) for f in self.leaf_features.get(l, ()))
                coefs.extend(float(c) for c in self.leaf_coeff.get(l, ()))
            lines += [
                "is_linear=1",
                "leaf_const=" + " ".join("%.17g" % v for v in self.leaf_const),
                "num_features=" + " ".join(str(v) for v in nf),
                "leaf_features=" + " ".join(str(v) for v in feats),
                "leaf_coeff=" + " ".join("%.17g" % v for v in coefs),
            ]
        return "\n".join(lines)

    @classmethod
    def from_text(cls, block: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in block.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        t = cls(int(kv["num_leaves"]))
        t.num_cat = int(kv.get("num_cat", "0"))
        t.shrinkage = float(kv.get("shrinkage", "1"))

        def arr(key: str, dtype, size: int) -> np.ndarray:
            if key not in kv or kv[key] == "":
                return np.zeros(size, dtype=dtype)
            return np.asarray([float(x) for x in kv[key].split()], dtype=dtype)

        L = t.num_leaves
        t.leaf_value = arr("leaf_value", np.float64, L)
        t.leaf_weight = arr("leaf_weight", np.float64, L)
        t.leaf_count = arr("leaf_count", np.int64, L)
        if L > 1:
            n = L - 1
            t.split_feature = arr("split_feature", np.int32, n)
            t.split_gain = arr("split_gain", np.float32, n)
            t.threshold = arr("threshold", np.float64, n)
            t.decision_type = arr("decision_type", np.int8, n)
            t.left_child = arr("left_child", np.int32, n)
            t.right_child = arr("right_child", np.int32, n)
            t.internal_value = arr("internal_value", np.float64, n)
            t.internal_weight = arr("internal_weight", np.float64, n)
            t.internal_count = arr("internal_count", np.int64, n)
            if kv.get("cat_threshold"):
                for item in kv["cat_threshold"].split(";"):
                    k, cats = item.split(":")
                    t.cat_threshold[int(k)] = np.asarray(
                        [int(c) for c in cats.split(",") if c], dtype=np.int64)
        if kv.get("is_linear", "0").strip() == "1":
            t.is_linear = True
            t.leaf_const = arr("leaf_const", np.float64, L)
            nf = arr("num_features", np.int64, L).astype(int)
            feats = arr("leaf_features", np.int64, int(nf.sum()))
            coefs = arr("leaf_coeff", np.float64, int(nf.sum()))
            pos = 0
            for l in range(L):
                k = int(nf[l])
                if k:
                    t.leaf_features[l] = feats[pos:pos + k].astype(np.int64)
                    t.leaf_coeff[l] = coefs[pos:pos + k]
                pos += k
        return t
